"""The north-star integration: a peer TRAINS the flagship model sharded over a
dp×tp×sp mesh (ring attention, tensor-parallel kernels) and averages its parameters
with a swarm peer through the ICI bridge — sharded compute below, swarm collective
above, one host staging per round (SURVEY §5 two-tier backend, BASELINE.md)."""

import numpy as np
import optax

import jax

from hivemind_tpu.averaging import DecentralizedAverager, MeshAverager
from hivemind_tpu.models import AlbertConfig, make_synthetic_mlm_batch, make_train_step
from hivemind_tpu.parallel import batch_sharding, make_mesh, params_shardings

from swarm_utils import launch_dht_swarm, shutdown_all


def test_sharded_training_with_swarm_averaging():
    mesh = make_mesh(dp=2, tp=2, sp=2)
    config = AlbertConfig.tiny(mesh=mesh)
    optimizer = optax.adamw(1e-3)
    model, train_step = make_train_step(config, optimizer, masked_loss_fraction=0.25)

    batch = make_synthetic_mlm_batch(jax.random.PRNGKey(0), config, batch_size=4, seq_len=32)
    params = model.init(jax.random.PRNGKey(1), batch["input_ids"])["params"]
    params = jax.device_put(params, params_shardings(params, mesh))
    opt_state = optimizer.init(params)
    batch = jax.device_put(batch, batch_sharding(mesh))

    with mesh:
        step = jax.jit(train_step)
        for _ in range(2):  # local sharded training before the swarm round
            loss, params, opt_state = step(params, opt_state, batch)
    assert np.isfinite(float(loss))

    dhts = launch_dht_swarm(2)
    mesh_peer = host_peer = None
    try:
        common = dict(
            prefix="ici_train", start=True, target_group_size=2,
            min_matchmaking_time=1.0, request_timeout=1.0,
        )
        mesh_peer = MeshAverager(params, mesh, dhts[0], **common)
        # the "other pod": host-resident parameters with the same schema
        rng = np.random.RandomState(7)
        host_leaves = [
            np.asarray(leaf, np.float32) + rng.randn(*leaf.shape).astype(np.float32) * 0.01
            for leaf in jax.tree_util.tree_leaves(params)
        ]
        host_peer = DecentralizedAverager([t.copy() for t in host_leaves], dhts[1], **common)

        trained_leaves = [np.asarray(l, np.float32) for l in jax.tree_util.tree_leaves(params)]
        controls = [a.step(wait=False, timeout=30) for a in (mesh_peer, host_peer)]
        for control in controls:
            assert control.result(timeout=60) is not None

        # both sides converged to the cross-pod average
        averaged_tree = mesh_peer.device_tree
        averaged_leaves = jax.tree_util.tree_leaves(averaged_tree)
        with host_peer.get_tensors() as host_now:
            for mine, theirs, trained, host_orig in zip(
                averaged_leaves, host_now, trained_leaves, host_leaves
            ):
                expected = (trained + host_orig) / 2.0
                np.testing.assert_allclose(np.asarray(mine), expected, rtol=1e-4, atol=1e-5)
                np.testing.assert_allclose(theirs, expected, rtol=1e-4, atol=1e-5)

        # the averaged tree kept its shardings: training continues sharded
        q_kernel = averaged_tree["shared_layer"]["query"]["kernel"]
        assert "tp" in str(q_kernel.sharding.spec)
        with mesh:
            loss2, _params, _opt_state = jax.jit(train_step)(averaged_tree, opt_state, batch)
        assert np.isfinite(float(loss2))
    finally:
        shutdown_all([obj for obj in (mesh_peer, host_peer) if obj is not None], dhts)
