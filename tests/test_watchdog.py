"""Event-loop watchdog (ISSUE 8): a deliberately injected ~250 ms loop block is
detected with the blocking frame visible in the captured stack, healthy loops
count zero stalls, executor backlogs are gauged, and the process-wide
registration is idempotent."""

import asyncio
import time

from hivemind_tpu.telemetry.registry import MetricsRegistry
from hivemind_tpu.telemetry.tracing import trace
from hivemind_tpu.telemetry import watchdog as watchdog_module
from hivemind_tpu.telemetry.watchdog import (
    EventLoopWatchdog,
    active_watchdogs,
    ensure_watchdog,
    shutdown_all,
    watchdog_summary,
)


def _blocking_call_the_watchdog_must_name():
    time.sleep(0.25)  # the deliberately injected ≥250 ms event-loop block


async def test_watchdog_detects_injected_block_and_names_the_frame():
    registry = MetricsRegistry()
    loop = asyncio.get_running_loop()
    watchdog = EventLoopWatchdog(
        loop, name="under-test", interval=0.02, stall_threshold=0.1, registry=registry
    )
    try:
        await asyncio.sleep(0.15)  # a few healthy heartbeats identify the loop thread
        with trace("allreduce.round", peer="me") as span:
            _blocking_call_the_watchdog_must_name()
        await asyncio.sleep(0.15)  # let the delayed heartbeat land and be observed
    finally:
        watchdog.shutdown()

    assert watchdog.stalls >= 1
    stall = watchdog.last_stall
    assert stall is not None and stall["threshold_s"] == 0.1
    # the captured stack names the exact blocking call, not just "loop was slow"
    assert "_blocking_call_the_watchdog_must_name" in stall["stack"], stall["stack"]
    assert "time.sleep(0.25)" in stall["stack"], stall["stack"]
    # the stall landed as an event on the span that was active on the loop thread
    events = {name: attrs for _t, name, attrs in (span.events or [])}
    assert "event_loop.stall" in events, span.events
    assert events["event_loop.stall"]["loop"] == "under-test"
    assert "time.sleep" in events["event_loop.stall"]["frame"]
    # metrics: the stall is counted and the ~250 ms lag reached the histogram
    assert registry.get("hivemind_event_loop_stalls_total").value(loop="under-test") >= 1
    lag = registry.get("hivemind_event_loop_lag_seconds").labels("under-test")
    assert lag.count >= 2  # healthy beats + the stalled one
    assert watchdog.max_lag >= 0.2


async def test_healthy_loop_counts_zero_stalls():
    registry = MetricsRegistry()
    loop = asyncio.get_running_loop()
    # the threshold stays generous: a loaded CI box can delay THREAD scheduling
    # by hundreds of ms, which is host jitter, not an event-loop stall
    watchdog = EventLoopWatchdog(
        loop, name="healthy", interval=0.02, stall_threshold=2.0, registry=registry
    )
    try:
        for _ in range(10):
            await asyncio.sleep(0.02)  # cooperative awaits only: no stall
    finally:
        watchdog.shutdown()
    assert watchdog.stalls == 0
    assert registry.get("hivemind_event_loop_stalls_total").value(loop="healthy") == 0
    assert registry.get("hivemind_event_loop_lag_seconds").labels("healthy").count >= 3


async def test_executor_queue_depth_gauge():
    registry = MetricsRegistry()
    loop = asyncio.get_running_loop()
    watchdog = EventLoopWatchdog(
        loop, name="gauges", interval=0.02, stall_threshold=1.0, registry=registry, start=False
    )
    # asyncio_utils is imported by the package, so its pools are always visible
    import hivemind_tpu.utils.asyncio_utils  # noqa: F401

    watchdog._sample_executors()
    gauge = registry.get("hivemind_executor_queue_depth")
    assert gauge is not None
    depths = {key[0]: child.value for key, child in gauge.series()}
    assert "blocking" in depths and depths["blocking"] >= 0
    assert "lock" in depths


async def test_ensure_watchdog_is_idempotent_per_loop_and_respects_kill_switch():
    loop = asyncio.get_running_loop()
    shutdown_all()
    try:
        first = ensure_watchdog(loop, name="shared")
        second = ensure_watchdog(loop, name="other-name")
        assert first is not None and second is first  # one loop, one watchdog
        assert first in active_watchdogs()
        summary = watchdog_summary()
        assert summary["loops"] == ["shared"] and summary["stalls"] == 0
        assert summary["max_lag_s"] >= 0.0

        original = watchdog_module.enabled
        watchdog_module.enabled = False
        try:
            shutdown_all()
            assert ensure_watchdog(loop, name="disabled") is None
            assert active_watchdogs() == []
        finally:
            watchdog_module.enabled = original
    finally:
        shutdown_all()


def test_watchdogs_armed_by_swarm_components():
    """DHT startup arms the process-wide watchdog on the shared loop (the
    averager and MoE server share it) — no operator action required."""
    from hivemind_tpu.dht import DHT

    shutdown_all()
    dht = DHT(start=True)
    try:
        assert active_watchdogs(), "starting a DHT must arm the event-loop watchdog"
    finally:
        dht.shutdown()
        shutdown_all()
