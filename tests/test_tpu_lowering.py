"""AOT-lower the Pallas kernels and the sharded train step for the TPU target on a
CPU-only host (VERDICT r4 next-round #3): `jax.export` with platforms=("tpu",) runs
the full Pallas→Mosaic lowering path — kernel tiling rules, shape/layout checks,
custom-call emission — without executing anything, so "compiles onto the MXU"
claims are validated up to (and excluding) runtime even while no chip is
reachable. What this does NOT cover, by construction: numerical execution on a
real TPU and performance (bench.py's on-device validation covers those the first
round the tunnel heals)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import export

from hivemind_tpu.ops.pallas_attention import flash_attention, flash_attention_lse
from hivemind_tpu.ops.pallas_quantization import (
    pallas_blockwise_dequantize,
    pallas_blockwise_quantize,
)


def _export_for_tpu(fn, *args):
    return export.export(jax.jit(fn), platforms=("tpu",))(*args)


def _assert_mosaic_lowered(exported):
    assert "tpu" in [p.lower() for p in exported.platforms]
    text = exported.mlir_module()
    assert "tpu_custom_call" in text or "mosaic" in text.lower(), (
        "the Pallas kernel did not lower through Mosaic for the TPU target"
    )


def test_flash_attention_forward_lowers_for_tpu():
    q = jax.ShapeDtypeStruct((2, 4, 256, 64), jnp.bfloat16)
    exported = _export_for_tpu(lambda a, b, c: flash_attention(a, b, c, causal=True), q, q, q)
    _assert_mosaic_lowered(exported)


def test_flash_attention_lse_lowers_for_tpu():
    q = jax.ShapeDtypeStruct((1, 2, 512, 64), jnp.float32)
    exported = _export_for_tpu(lambda a, b, c: flash_attention_lse(a, b, c), q, q, q)
    _assert_mosaic_lowered(exported)


def test_flash_attention_backward_lowers_for_tpu():
    q = jax.ShapeDtypeStruct((1, 2, 256, 64), jnp.float32)

    def loss(a, b, c):
        return jnp.sum(flash_attention(a, b, c, causal=True))

    exported = _export_for_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, q, q)
    _assert_mosaic_lowered(exported)


def test_blockwise_quantization_kernels_lower_for_tpu():
    flat = jax.ShapeDtypeStruct((1 << 16,), jnp.float32)
    exported = _export_for_tpu(lambda x: pallas_blockwise_quantize(x, block_size=4096), flat)
    _assert_mosaic_lowered(exported)

    codes = jax.ShapeDtypeStruct((16, 4096), jnp.int8)
    absmax = jax.ShapeDtypeStruct((16,), jnp.float32)
    exported = _export_for_tpu(
        lambda c, a: pallas_blockwise_dequantize(c, a, block_size=4096), codes, absmax
    )
    _assert_mosaic_lowered(exported)


def test_sharded_albert_train_step_lowers_for_tpu():
    """The FULL flagship train step — dp×tp×sp sharded ALBERT MLM fwd+bwd+adamw —
    lowers for an 8-device TPU mesh from this CPU host: every collective, every
    sharding constraint, and the attention core pass TPU lowering."""
    import optax

    from hivemind_tpu.models import (
        AlbertConfig,
        make_synthetic_mlm_batch,
        make_train_step,
    )
    from hivemind_tpu.parallel import make_mesh, params_shardings
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(dp=2, tp=2, sp=2)
    config = AlbertConfig.tiny(mesh=mesh, num_heads=4)
    optimizer = optax.adamw(1e-4)
    model, train_step = make_train_step(config, optimizer, masked_loss_fraction=0.25)
    batch = make_synthetic_mlm_batch(jax.random.PRNGKey(0), config, 8, 64)
    params = model.init(jax.random.PRNGKey(1), batch["input_ids"])["params"]
    opt_state = optimizer.init(params)

    shardings = params_shardings(params, mesh)
    params = jax.device_put(params, shardings)
    batch = jax.device_put(batch, NamedSharding(mesh, P("dp", "sp")))
    with mesh:
        exported = export.export(jax.jit(train_step), platforms=("tpu",))(
            params, opt_state, batch
        )
    assert "tpu" in [p.lower() for p in exported.platforms]
    assert exported.nr_devices == 8
    # the sharded step really carries cross-device communication for the mesh
    text = exported.mlir_module()
    assert "sharding" in text, "no sharding annotations survived lowering"


def test_sharded_train_step_with_flash_core_lowers_for_tpu(monkeypatch):
    """The composition that actually runs on a slice: the ring/flash attention
    core INSIDE the dp×tp×sp-sharded train step, exported for the TPU target
    (HIVEMIND_TPU_FORCE_FLASH overrides the backend gate for AOT workflows).
    The Mosaic custom call must survive into the sharded module."""
    import optax

    from hivemind_tpu.models import (
        AlbertConfig,
        make_synthetic_mlm_batch,
        make_train_step,
    )
    from hivemind_tpu.parallel import make_mesh, params_shardings
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(dp=2, tp=2, sp=2)
    # flash kernels tile (128, 128) blocks: use a flash-sized sequence
    config = AlbertConfig.tiny(mesh=mesh, num_heads=4, max_position=256)
    optimizer = optax.adamw(1e-4)
    model, train_step = make_train_step(config, optimizer, masked_loss_fraction=0.25)
    batch = make_synthetic_mlm_batch(jax.random.PRNGKey(0), config, 8, 256)
    params = model.init(jax.random.PRNGKey(1), batch["input_ids"])["params"]
    opt_state = optimizer.init(params)
    params = jax.device_put(params, params_shardings(params, mesh))
    batch = jax.device_put(batch, NamedSharding(mesh, P("dp", "sp")))
    # force the flash core only for the export TRACE (init above runs eagerly on
    # the CPU backend, where a non-interpret pallas_call cannot execute)
    monkeypatch.setenv("HIVEMIND_TPU_FORCE_FLASH", "1")
    with mesh:
        exported = export.export(jax.jit(train_step), platforms=("tpu",))(
            params, opt_state, batch
        )
    assert exported.nr_devices == 8
    text = exported.mlir_module()
    assert "tpu_custom_call" in text or "mosaic" in text.lower(), (
        "the flash core did not ride the sharded train step into the TPU module"
    )


def test_lowering_rejects_non_tpu_execution():
    """Executing a TPU-exported artifact on this CPU host must fail loudly (the
    artifact is for the TPU target) — guards against silently grading CPU
    numbers as TPU results."""
    q = jax.ShapeDtypeStruct((1, 2, 128, 64), jnp.float32)
    exported = _export_for_tpu(lambda a, b, c: flash_attention(a, b, c), q, q, q)
    array = np.zeros((1, 2, 128, 64), np.float32)
    with pytest.raises(Exception):
        exported.call(array, array, array)
