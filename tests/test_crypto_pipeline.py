"""Pipelined SecureChannel (multi-core AEAD data plane, VERDICT r2 next-round #5):
nonce/wire ordering under concurrent senders, threaded seal/open parity with the
inline path, bounded in-flight backpressure, and error propagation when the
transport dies mid-pipeline."""

import asyncio
import os

import pytest

from hivemind_tpu.p2p import crypto_channel
from hivemind_tpu.p2p.crypto_channel import HandshakeError, handshake
from hivemind_tpu.utils.crypto import Ed25519PrivateKey


async def _connected_pair():
    server_side = asyncio.Queue()

    async def on_connect(reader, writer):
        await server_side.put((reader, writer))

    server = await asyncio.start_server(on_connect, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    client_reader, client_writer = await asyncio.open_connection("127.0.0.1", port)
    server_reader, server_writer = await server_side.get()

    initiator_key, responder_key = Ed25519PrivateKey(), Ed25519PrivateKey()
    client_hs = handshake(client_reader, client_writer, initiator_key, is_initiator=True)
    server_hs = handshake(server_reader, server_writer, responder_key, is_initiator=False)
    (client, _), (peer, _) = await asyncio.gather(client_hs, server_hs)
    return client, peer, server


@pytest.mark.parametrize("aead_threads", ["0", "4"])
def test_pipeline_preserves_order_under_concurrent_senders(monkeypatch, aead_threads):
    """Interleaved small/large frames from many tasks must arrive in enqueue order
    with correct AEAD nonces — in both the inline and the thread-pool regime."""
    monkeypatch.setenv("HIVEMIND_AEAD_THREADS", aead_threads)

    async def scenario():
        client, peer, server = await _connected_pair()
        # distinct frames straddling the offload threshold so both regimes interleave
        frames = [
            b"%04d:" % i
            + bytes([i % 251]) * ((crypto_channel._OFFLOAD_THRESHOLD * 2) if i % 3 == 0 else 77)
            for i in range(60)
        ]

        async def send_slice(start):
            for i in range(start, len(frames), 4):
                await client.send(frames[i])

        # four concurrent senders; every frame decrypting proves nonce order and wire
        # order never diverged, and each sender's subsequence must arrive in order
        await asyncio.gather(*(send_slice(s) for s in range(4)))
        received = [await peer.recv() for _ in range(len(frames))]
        assert sorted(received) == sorted(frames)
        for start in range(4):
            sent = [frames[i] for i in range(start, len(frames), 4)]
            got = [f for f in received if f in set(sent)]
            assert got == sent, f"sender {start}'s frames arrived out of order"
        client.close()
        peer.close()
        server.close()
        await server.wait_closed()

    asyncio.run(scenario())


def test_threaded_aead_roundtrip_large_frames(monkeypatch):
    monkeypatch.setenv("HIVEMIND_AEAD_THREADS", "4")

    async def scenario():
        client, peer, server = await _connected_pair()
        payload = os.urandom(4 * 1024 * 1024)
        echoes = []

        async def echo_loop():
            for _ in range(6):
                echoes.append(await peer.recv())

        consumer = asyncio.create_task(echo_loop())
        for i in range(6):
            await client.send(payload[i:] if i else payload)
        await consumer
        assert echoes[0] == payload
        for i in range(1, 6):
            assert echoes[i] == payload[i:]
        client.close()
        peer.close()
        server.close()
        await server.wait_closed()

    asyncio.run(scenario())


def test_send_after_transport_death_raises(monkeypatch):
    monkeypatch.setenv("HIVEMIND_AEAD_THREADS", "0")

    async def scenario():
        client, peer, server = await _connected_pair()
        peer.close()  # remote vanishes
        with pytest.raises((ConnectionError, HandshakeError)):
            # the first sends may land in dead buffers; eventually the writer task
            # observes the broken pipe and every later send must raise
            for _ in range(200):
                await client.send(b"x" * 65536)
                await asyncio.sleep(0)
        client.close()
        server.close()
        await server.wait_closed()

    asyncio.run(scenario())


def test_recv_drains_prefetched_frames_before_raising(monkeypatch):
    """Frames already on the wire when the peer closes must still be delivered."""
    monkeypatch.setenv("HIVEMIND_AEAD_THREADS", "0")

    async def scenario():
        client, peer, server = await _connected_pair()
        for i in range(5):
            await client.send(f"frame-{i}".encode())
        await asyncio.sleep(0.2)  # let the frames reach the peer's socket
        client.close()
        got = []
        with pytest.raises((ConnectionError, asyncio.IncompleteReadError, HandshakeError)):
            while True:
                got.append(await peer.recv())
        assert got == [f"frame-{i}".encode() for i in range(5)]
        peer.close()
        server.close()
        await server.wait_closed()

    asyncio.run(scenario())


def test_corrupted_frame_fails_authentication(monkeypatch):
    monkeypatch.setenv("HIVEMIND_AEAD_THREADS", "0")

    async def scenario():
        client, peer, server = await _connected_pair()
        # bypass the channel: write a validly-framed but garbage ciphertext
        import struct

        garbage = os.urandom(64)
        client._writer.write(struct.pack(">I", len(garbage)) + garbage)
        await client._writer.drain()
        with pytest.raises(HandshakeError):
            await peer.recv()
        client.close()
        peer.close()
        server.close()
        await server.wait_closed()

    asyncio.run(scenario())


def test_tampered_large_frame_poisons_whole_channel(monkeypatch):
    """AEAD failure must be fatal regardless of frame size (round-3 advisor,
    crypto_channel.py:191): nonces are counters, so if one tampered OFFLOADED frame
    only killed its own recv(), later frames would still authenticate and an
    on-path attacker could selectively delete frames. After the tamper, every recv
    AND every send on the victim channel must fail."""
    monkeypatch.setenv("HIVEMIND_AEAD_THREADS", "4")

    async def scenario():
        import struct

        client, peer, server = await _connected_pair()
        await client.send(b"ok-1")
        assert await peer.recv() == b"ok-1"  # drain first so the raw write below can't race the pipelined writer
        # an on-path tamper: seal a large frame with the CORRECT next nonce, then
        # flip one ciphertext byte — framing stays valid, counters stay aligned
        nonce = struct.pack("<4xQ", client._send_counter)
        client._send_counter += 1
        big = bytes(range(256)) * (crypto_channel._OFFLOAD_THRESHOLD // 256 + 1)
        sealed = bytearray(client._send_aead.encrypt(nonce, big, None))
        sealed[1000] ^= 0xFF
        client._writer.write(struct.pack(">I", len(sealed)) + bytes(sealed))
        await client._writer.drain()
        await client.send(b"ok-2")  # valid in isolation — must never be delivered

        with pytest.raises(HandshakeError):
            await peer.recv()
        # the channel is poisoned: the tampered frame cannot be silently skipped
        with pytest.raises((HandshakeError, ConnectionError)):
            await peer.recv()
        # ... and the victim's send side is failed too
        with pytest.raises((ConnectionError, HandshakeError)):
            for _ in range(64):
                await peer.send(b"x")
                await asyncio.sleep(0)

        client.close()
        peer.close()
        server.close()
        await server.wait_closed()

    asyncio.run(scenario())


def test_two_parked_recv_waiters_both_unblock_on_reader_death(monkeypatch):
    """One reader-death sentinel must serve EVERY concurrent recv() (round-3
    advisor, crypto_channel.py:208): the sentinel is re-enqueued before raising, so
    a second parked waiter raises instead of hanging forever."""
    monkeypatch.setenv("HIVEMIND_AEAD_THREADS", "0")

    async def scenario():
        client, peer, server = await _connected_pair()
        waiters = [asyncio.create_task(peer.recv()) for _ in range(2)]
        await asyncio.sleep(0.1)  # both park on the empty recv queue
        client.close()
        done, pending = await asyncio.wait(waiters, timeout=5)
        assert not pending, "a parked recv() hung after reader death"
        for task in done:
            assert isinstance(
                task.exception(), (ConnectionError, HandshakeError, asyncio.IncompleteReadError)
            )
        peer.close()
        server.close()
        await server.wait_closed()

    asyncio.run(scenario())
