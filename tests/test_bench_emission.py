"""The official round artifact must always carry a legible metric.

The driver records only the last ~2000 characters of bench.py's output; round 4
embedded the probe log inside the single JSON line and truncated its own metric
away (VERDICT r4 weak #1). These tests pin the contract: whatever diagnostics a
round accumulates, the final stdout line is compact, metric-first JSON that
survives a 2000-char tail capture."""

import importlib.util
import io
import json
import os

_BENCH_PATH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py")
_spec = importlib.util.spec_from_file_location("bench", _BENCH_PATH)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _bloated_result() -> dict:
    """A worst-case round result: three probe points with verbatim hang errors,
    repeated measurement failures, bracketing host controls — the exact shape
    that defeated the round-4 artifact."""
    probe_errors = [
        {
            "attempt": i,
            "rc": None,
            "stderr": "probe hung >120s (tunnel wedged); partial stderr: " + "x" * 400,
        }
        for i in range(3)
    ]
    control = {
        "unix_time": 1753800000.0,
        "loadavg": [3.12, 2.98, 2.5],
        "cpu_count": 1,
        "matmul_gflops": 10.45,
        "aead_seal_mb_s": 1333.7,
    }
    return {
        "metric": "albert_base_mlm_tokens_per_sec_per_chip",
        "value": 1234.5,
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "tpu_unavailable": True,
        "fallback": "cpu",
        "extra": {
            "device": "cpu",
            "batch_size": 4,
            "remat": False,
            "seq_len": 128,
            "final_loss": 7.1234,
            "averaging_gbps_per_peer": 0.61,
            "averaging_extra": {"num_peers": 4, "rounds": 3, "detail": "y" * 600},
            "host_control": {"at_start": control, "at_end": control},
        },
        "tpu_probe_log": [
            {
                "when": label,
                "unix_time": 1753800000.0 + 600 * i,
                "loadavg": [3.0, 3.0, 3.0],
                "reachable": False,
                "errors": probe_errors,
            }
            for i, label in enumerate(["round_start", "mid_round_post_averaging", "pre_emit"])
        ],
        "tpu_measure_errors": ["measurement subprocess hung >1800s (runtime wedged mid-run)"] * 2,
    }


def test_final_line_survives_2000_char_tail():
    out, err = io.StringIO(), io.StringIO()
    bench.emit(_bloated_result(), out=out, err=err)

    tail = out.getvalue()[-2000:]  # what the driver actually keeps
    last_line = tail.strip().splitlines()[-1]
    parsed = json.loads(last_line)
    assert parsed["metric"] == "albert_base_mlm_tokens_per_sec_per_chip"
    assert parsed["value"] == 1234.5
    assert parsed["unit"] == "tokens/s"
    assert parsed["vs_baseline"] == 0.0
    assert parsed["tpu_unavailable"] is True
    # probe outcomes survive in summarized form
    probes = parsed["extra"]["tpu_probes"]
    assert [p["reachable"] for p in probes] == [False, False, False]

    # the full diagnostics are preserved, on stderr
    full = json.loads(err.getvalue())
    assert full["tpu_probe_log"][0]["errors"][0]["stderr"].startswith("probe hung")


def test_compact_line_bounded_even_when_pathological():
    result = _bloated_result()
    # a pathologically long device string + many probes: the line must still fit
    result["extra"]["device"] = "d" * 3000
    result["tpu_probe_log"] = result["tpu_probe_log"] * 20
    line = bench.compact_result(result)
    assert len(line) <= 1500
    parsed = json.loads(line)
    assert parsed["metric"] == "albert_base_mlm_tokens_per_sec_per_chip"
    assert parsed["value"] == 1234.5


def test_bench_artifact_embeds_telemetry_snapshot():
    """ISSUE 2: every BENCH artifact carries a telemetry snapshot — the bench
    process's registry plus the averaging swarm's (shipped via its JSON extra) —
    while the compact stdout line stays bounded."""
    from hivemind_tpu.telemetry import REGISTRY

    REGISTRY.counter("bench_emission_probe_total", "test counter").inc(5)
    averaging = {
        "value": 0.61,
        "extra": {"telemetry": {"hivemind_averaging_matchmaking_rounds_total": {
            "type": "counter", "series": {"outcome=assembled": 8}}}},
    }
    try:
        section = bench.telemetry_section(averaging)
    finally:
        REGISTRY.unregister("bench_emission_probe_total")  # keep the global registry clean
    assert section["bench_process"]["metrics"]["bench_emission_probe_total"]["series"]["_"] == 5
    assert section["averaging_swarm"]["hivemind_averaging_matchmaking_rounds_total"]["series"][
        "outcome=assembled"] == 8

    result = _bloated_result()
    result["telemetry"] = section
    out, err = io.StringIO(), io.StringIO()
    bench.emit(result, out=out, err=err)
    # the full stderr artifact carries the snapshot verbatim…
    full = json.loads(err.getvalue())
    assert full["telemetry"]["bench_process"]["metrics"]["bench_emission_probe_total"]
    assert full["telemetry"]["averaging_swarm"]
    # …and the compact driver line still fits and leads with the metric
    last_line = out.getvalue().strip().splitlines()[-1]
    assert len(last_line) <= 1500
    assert json.loads(last_line)["metric"] == "albert_base_mlm_tokens_per_sec_per_chip"


def test_telemetry_section_survives_missing_averaging():
    section = bench.telemetry_section(None)
    assert "bench_process" in section or "error" in section
    assert "averaging_swarm" not in section


def test_compact_line_keeps_tpu_success_fields():
    result = {
        "metric": "albert_base_mlm_tokens_per_sec_per_chip",
        "value": 30000.0,
        "unit": "tokens/s",
        "vs_baseline": 1.07,
        "extra": {
            "device": "TPU v5 lite",
            "mfu": 0.374,
            "batch_size": 256,
            "remat": True,
            "seq_len": 512,
            "attention": "flash",
            "attention_tokens_per_sec": {"flash": 30000.0, "plain": 21000.0},
        },
    }
    parsed = json.loads(bench.compact_result(result))
    assert parsed["extra"]["mfu"] == 0.374
    assert parsed["extra"]["attention"] == "flash"
    assert parsed["vs_baseline"] == 1.07


def test_bench_artifact_embeds_ledger_and_watchdog_attribution():
    """ISSUE 8: the averaging swarm's ledger + watchdog rollup rides the BENCH
    artifact, so a perf regression carries attribution (rounds, per-phase
    mean/p95, straggler scores, stall count, max loop lag), not just the
    headline number."""
    averaging = {
        "value": 0.3,
        "extra": {
            "telemetry": {},
            "attribution": {
                "ledger": {
                    "rounds": 12,
                    "total_s": {"mean": 0.8, "p95": 1.4},
                    "matchmaking_wait_s": {"mean": 0.4, "p95": 0.9},
                    "stragglers": {"peerX": {"rounds_slowest": 7, "excess_s": 2.1}},
                },
                "watchdog": {"loops": ["hmtpu-loop"], "stalls": 0, "max_lag_s": 0.004},
            },
        },
    }
    section = bench.telemetry_section(averaging)
    assert section["attribution"]["ledger"]["rounds"] == 12
    assert section["attribution"]["ledger"]["total_s"]["p95"] == 1.4
    assert section["attribution"]["watchdog"]["stalls"] == 0

    result = _bloated_result()
    result["extra"]["averaging_extra"] = dict(averaging["extra"])
    # main() strips telemetry/attribution from the copied extra (they land once,
    # under result["telemetry"]): mirror that here and assert the invariant
    result["extra"]["averaging_extra"] = {
        k: v for k, v in result["extra"]["averaging_extra"].items()
        if k not in ("telemetry", "attribution")
    }
    result["telemetry"] = section
    out, err = io.StringIO(), io.StringIO()
    bench.emit(result, out=out, err=err)
    full = json.loads(err.getvalue())
    assert full["telemetry"]["attribution"]["ledger"]["stragglers"]["peerX"]["rounds_slowest"] == 7
    assert "attribution" not in full["extra"]["averaging_extra"]


def test_benchmark_averaging_smoke_uniform8():
    """ISSUE 11: the quantized averaging tier end-to-end in --smoke mode —
    2 peers negotiate uniform8 links (with error-feedback residuals) through
    the real DHT + matchmaking + butterfly path; any failed step exits nonzero,
    so a quantized-wire regression fails tier-1 loudly. Mirrors the fp16 smoke
    in test_partition_equivalence.py (bench.py's `_averaging_gbps_q8` runs the
    same codec at the full 4-peer/4M config)."""
    import subprocess
    import sys

    script = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks", "benchmark_averaging.py",
    )
    run = subprocess.run(
        [sys.executable, script, "--smoke", "--compression", "uniform8"],
        timeout=180,
        capture_output=True,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert run.returncode == 0, f"smoke benchmark failed:\n{run.stdout[-2000:]}\n{run.stderr[-2000:]}"
    payload = next(line for line in run.stdout.splitlines() if line.startswith("{"))
    result = json.loads(payload)
    assert result["extra"]["success_rate"] == 1.0
    assert result["extra"]["compression"] == "uniform_8bit"


def test_benchmark_llama_serving_smoke():
    """ISSUE 10: the serving data path end-to-end (checkpoint load + Server +
    RemoteSequential KV-cache decode over real RPC) — --smoke exits nonzero on
    any failed request or if the serving wire-bytes counters did not move, so a
    compressed-RPC/batching regression fails tier-1 loudly (mirrors the
    benchmark_averaging smoke pattern)."""
    import subprocess
    import sys

    script = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks", "benchmark_llama_serving.py",
    )
    run = subprocess.run(
        [sys.executable, script, "--smoke", "--platform", "cpu"],
        timeout=240,
        capture_output=True,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert run.returncode == 0, f"smoke benchmark failed:\n{run.stdout[-2000:]}\n{run.stderr[-2000:]}"
    payload = next(line for line in run.stdout.splitlines() if line.startswith("{"))
    result = json.loads(payload)
    assert result["metric"] == "llama_checkpoint_decode"
    # any failed request exits nonzero before the JSON prints (asserted above)
    wire = result["extra"]["wire_bytes_per_token"]
    assert wire["sent"] > 0 and wire["received"] > 0
    # the default A/B config rides fp16 activations on the wire
    assert result["extra"]["activation_compression"] == "float16"


def test_benchmark_llama_multi_client_smoke():
    """ISSUE 13: the skewed multi-client load generator end to end — one hot
    client + a background client over TWO replicas with fair-share admission
    armed and one replica crash-killed mid-run. --smoke exits nonzero on any
    non-shed client-visible failure, on a background-client shed (fair-share
    violated), or on a client decoding zero tokens."""
    import subprocess
    import sys

    script = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks", "benchmark_llama_serving.py",
    )
    run = subprocess.run(
        [sys.executable, script, "--smoke", "--multi_client", "1", "--replicas", "2",
         "--kill_replica_at", "0.5", "--client_rate", "40", "--platform", "cpu"],
        timeout=300,
        capture_output=True,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert run.returncode == 0, f"smoke benchmark failed:\n{run.stdout[-2000:]}\n{run.stderr[-2000:]}"
    payload = next(line for line in run.stdout.splitlines() if line.startswith("{"))
    result = json.loads(payload)
    assert result["metric"] == "llama_multi_client_decode"
    clients = result["extra"]["clients"]
    assert set(clients) == {"hot", "bg0"}
    for name, entry in clients.items():
        assert entry["failures"] == [], (name, entry)
        assert entry["tokens"] > 0 and "p99_ms" in entry, (name, entry)
    # the kill actually happened and the replica set was real
    assert result["extra"]["killed_replica_at_s"] is not None
    assert result["extra"]["replicas"] == 2
    # background client untouched by the hot client's saturation
    assert clients["bg0"]["sheds"] == 0


def test_benchmark_swarm_sim_smoke():
    """ISSUE 12: the swarm simulator end-to-end in --smoke mode — a ~100-peer
    composite (DHT fan-out under churn + link-scoped chaos, matchmaking
    convergence across a partition, beam search vs oracle) plus a
    same-seed-twice determinism double-run; any failed invariant exits nonzero,
    so a sim/transport regression fails tier-1 loudly (mirrors the averaging
    and serving smoke patterns)."""
    import subprocess
    import sys

    script = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks", "benchmark_swarm_sim.py",
    )
    run = subprocess.run(
        [sys.executable, script, "--smoke", "--seed", "17"],
        timeout=420,
        capture_output=True,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert run.returncode == 0, f"smoke benchmark failed:\n{run.stdout[-2000:]}\n{run.stderr[-2000:]}"
    payload = next(line for line in run.stdout.splitlines() if line.startswith("{"))
    result = json.loads(payload)
    assert result["metric"] == "swarm_sim_peers"
    assert result["value"] >= 90  # ~100 peers simulated across the composite
    assert result["extra"]["deterministic"] is True
    assert result["extra"]["recall_at_beam"] >= 0.95
    assert result["extra"]["failures"] == []


def test_bench_artifact_compact_line_carries_swarm_sim():
    """The swarm-sim scale numbers ride the compact driver line (and drop
    early under pressure, before the headline metrics)."""
    result = _bloated_result()
    result["extra"]["swarm_sim"] = {
        "peers": 300, "sim_seconds_per_wall_second": 0.62,
        "recall_at_beam": 1.0, "deterministic": True, "get_success_rate": 1.0,
    }
    parsed = json.loads(bench.compact_result(result))
    assert parsed["extra"]["swarm_sim"]["peers"] == 300
    assert parsed["extra"]["swarm_sim"]["deterministic"] is True
    # under pathological pressure the line still fits and leads with the metric
    result["extra"]["device"] = "d" * 3000
    line = bench.compact_result(result)
    assert len(line) <= 1500
    assert json.loads(line)["metric"] == "albert_base_mlm_tokens_per_sec_per_chip"


def test_bench_artifact_embeds_serving_attribution():
    """ISSUE 9: the llama-serving swarm's per-request attribution summary rides
    the BENCH artifact under telemetry.serving — per-expert p50/p95, phase
    decomposition, batch occupancy, shed count."""
    serving = {
        "value": 19.0,
        "extra": {
            "serving": {
                "requests": 98, "errors": 0, "sheds": 0,
                "phases": {
                    "total_s": {"mean": 0.05, "p50": 0.04, "p95": 0.11},
                    "compute_s": {"mean": 0.03, "p50": 0.03, "p95": 0.06},
                },
                "batch_occupancy": {"mean": 0.002, "p50": 0.002, "p95": 0.002},
                "experts": {"lb.0": {"requests": 49, "p95_s": 0.06, "p50_s": 0.04}},
            },
        },
    }
    section = bench.telemetry_section(None, serving)
    assert section["serving"]["requests"] == 98
    assert section["serving"]["experts"]["lb.0"]["p95_s"] == 0.06

    result = _bloated_result()
    result["telemetry"] = section
    out, err = io.StringIO(), io.StringIO()
    bench.emit(result, out=out, err=err)
    full = json.loads(err.getvalue())
    assert full["telemetry"]["serving"]["phases"]["compute_s"]["p95"] == 0.06
    # missing serving stays absent, never a crash
    assert "serving" not in bench.telemetry_section(None, None)
