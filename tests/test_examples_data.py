"""examples/albert data pipeline: self-contained corpus tokenizer + BERT-style
masking statistics, and the sampler fallback chain; 2-peer smoke run of the
actual run_trainer.py recipe."""

import os
import re
import subprocess
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples", "albert"))

from data import MASK, NUM_SPECIAL, TextMLMDataset, make_batch_sampler  # noqa: E402


@pytest.fixture
def corpus(tmp_path):
    path = tmp_path / "corpus.txt"
    words = ["alpha", "beta", "gamma", "delta", "epsilon"] * 400
    rng = np.random.RandomState(0)
    rng.shuffle(words)
    path.write_text(" ".join(words))
    return str(path)


def test_text_mlm_dataset_masking(corpus):
    dataset = TextMLMDataset(corpus, vocab_size=64, seq_len=32, mask_prob=0.15)
    rng = np.random.RandomState(1)
    batch = dataset.sample_batch(rng, batch_size=64)
    assert batch["input_ids"].shape == batch["labels"].shape == batch["mlm_mask"].shape == (64, 32)
    assert batch["labels"].min() >= NUM_SPECIAL  # only real words in this corpus
    assert batch["labels"].max() < 64

    # unselected positions are untouched
    untouched = ~batch["mlm_mask"]
    np.testing.assert_array_equal(batch["input_ids"][untouched], batch["labels"][untouched])

    # BERT 80/10/10: ~80% of selected positions are [MASK]; ~15% selected overall
    selected = batch["mlm_mask"]
    rate = selected.mean()
    assert 0.10 < rate < 0.20, rate
    mask_fraction = (batch["input_ids"][selected] == MASK).mean()
    assert 0.7 < mask_fraction < 0.9, mask_fraction
    # and some positions differ from the label without being [MASK] (random 10%)
    changed = (batch["input_ids"] != batch["labels"]) & selected & (batch["input_ids"] != MASK)
    assert changed.sum() > 0


def test_make_batch_sampler_chain(corpus):
    from hivemind_tpu.models import AlbertConfig

    config = AlbertConfig.tiny(max_position=32)
    real = make_batch_sampler(config, seq_len=32, dataset_path=corpus, seed=3)
    batch = real(8)
    assert batch["input_ids"].shape == (8, 32)

    synthetic = make_batch_sampler(config, seq_len=32, seed=3)
    batch = synthetic(4)
    assert batch["input_ids"].shape == (4, 32)
    assert set(batch) == {"input_ids", "labels", "mlm_mask"}


def test_shared_vocab_across_peers(tmp_path, corpus):
    """Two peers with DIFFERENT corpora get an identical token mapping through the
    shared vocab file (the collaborative-training requirement)."""
    vocab_path = str(tmp_path / "vocab.txt")
    first = TextMLMDataset(corpus, vocab_size=64, seq_len=16, vocab_path=vocab_path)

    other_corpus = tmp_path / "other.txt"
    other_corpus.write_text("gamma beta zeta " * 200)  # different corpus, different stats
    second = TextMLMDataset(str(other_corpus), vocab_size=64, seq_len=16, vocab_path=vocab_path)
    assert first.vocab == second.vocab  # mapping came from the shared file

    import pytest as _pytest

    from data import make_batch_sampler

    with _pytest.raises(ValueError, match="hf_tokenizer"):
        from hivemind_tpu.models import AlbertConfig

        make_batch_sampler(AlbertConfig.tiny(max_position=16), 16, hf_tokenizer="bert-base-uncased")


def test_run_trainer_causal_model_smoke():
    """--model causal trains the decoder-only family through the same recipe: a
    single peer advances solo epochs and exits cleanly."""
    repo = os.path.join(os.path.dirname(__file__), "..")
    script = os.path.join(repo, "examples", "albert", "run_trainer.py")
    env = {**os.environ, "PYTHONPATH": repo}
    run = subprocess.run(
        [sys.executable, script, "--model", "causal", "--tiny", "--platform", "cpu",
         "--run_id", "causal_smoke", "--max_steps", "6", "--target_batch_size", "32",
         "--batch_size", "16", "--seq_len", "64", "--matchmaking_time", "0.5", "--seed", "0"],
        stderr=subprocess.PIPE, text=True, cwd=repo, timeout=180, env=env,
    )
    assert run.returncode == 0, run.stderr[-3000:]
    assert re.search(r"training finished after 6 steps at epoch (\d+)", run.stderr), run.stderr[-2000:]


@pytest.mark.slow  # ~50 s; the one-process trainer path stays covered by
# test_run_trainer_causal_model_smoke, and two-peer swarm training by test_optimizer.py
def test_run_trainer_two_peer_smoke():
    """The flagship recipe end-to-end: two run_trainer.py processes (tiny config,
    synthetic data) form a swarm, advance epochs together, and exit cleanly after
    max_steps (regression: the trainer used to hang on background threads)."""
    repo = os.path.join(os.path.dirname(__file__), "..")
    script = os.path.join(repo, "examples", "albert", "run_trainer.py")
    common = [
        sys.executable, script, "--tiny", "--platform", "cpu",
        "--run_id", "smoke", "--max_steps", "16", "--target_batch_size", "64",
        "--batch_size", "16", "--seq_len", "64", "--matchmaking_time", "1.0",
    ]
    env = {**os.environ, "PYTHONPATH": repo}
    first = subprocess.Popen(
        common + ["--seed", "0"], stderr=subprocess.PIPE, text=True, cwd=repo, env=env
    )
    try:
        maddr = None
        deadline = time.monotonic() + 120
        lines = []
        while time.monotonic() < deadline:
            line = first.stderr.readline()
            lines.append(line)
            found = re.search(r"--initial_peers (\S+)", line)
            if found:
                maddr = found.group(1)
                break
        assert maddr, f"first peer never announced its address: {''.join(lines)[-2000:]}"

        # the monitor joins as a non-training observer and must see swarm progress
        monitor_script = os.path.join(repo, "examples", "albert", "run_training_monitor.py")
        monitor = subprocess.Popen(
            [sys.executable, monitor_script, "--run_id", "smoke", "--initial_peers",
             maddr, "--refresh_period", "2.0", "--max_reports", "1"],
            stderr=subprocess.PIPE, text=True, cwd=repo, env=env,
        )

        # generous deadlines: at the tail of a full-suite run this test shares
        # the one core with compile-heavy neighbors and can legitimately take
        # several minutes (it passes alone in ~1) — a timeout here is a flake,
        # not a hang signal
        second = subprocess.run(
            common + ["--seed", "1", "--initial_peers", maddr],
            stderr=subprocess.PIPE, text=True, cwd=repo, timeout=420, env=env,
        )
        first_err = first.communicate(timeout=240)[1]
        logs = "".join(lines) + (first_err or "") + (second.stderr or "")
        assert second.returncode == 0, logs[-3000:]
        assert first.returncode == 0, logs[-3000:]
        finished = re.findall(r"training finished after 16 steps at epoch (\d+)", logs)
        assert len(finished) == 2, logs[-3000:]
        # 2 peers x 16 steps x 16 samples = 512 samples = 8 virtual epochs of 64:
        # both peers must have transitioned epochs collaboratively at least twice
        assert all(int(epoch) >= 2 for epoch in finished), finished

        monitor_err = monitor.communicate(timeout=60)[1]
        assert monitor.returncode == 0, monitor_err[-2000:]
        assert re.search(r"epoch \d+: \d+ peers, \d+ samples accumulated", monitor_err), (
            monitor_err[-2000:]
        )
    finally:
        if first.poll() is None:
            first.kill()
        if "monitor" in locals() and monitor.poll() is None:
            monitor.kill()
