"""Wires tools/check_metric_docs.py into the suite (ISSUE 9 satellite): a
registered ``hivemind_*`` metric missing from docs/observability.md's catalog
fails tier-1 (the catalog already drifted once — a queue-depth gauge documented
under a wrong name)."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import check_metric_docs


def test_every_registered_metric_is_documented():
    failures, warnings = check_metric_docs.check()
    assert not failures, (
        "metric-catalog violations (see tools/check_metric_docs.py):\n"
        + "\n".join(failures)
    )
    for warning in warnings:
        print(f"note: {warning}")


def test_lint_catches_undocumented_and_stale_names(tmp_path):
    """The lint actually detects (a) a registered-but-undocumented metric and
    (b) a documented-but-unregistered catalog row."""
    package = tmp_path / "pkg"
    package.mkdir()
    (package / "mod.py").write_text(
        'REGISTRY.counter("hivemind_phantom_total", "doc", ("x",))\n'
        'REGISTRY.gauge("hivemind_documented_gauge", "doc")\n'
    )
    doc = tmp_path / "observability.md"
    doc.write_text(
        "| `hivemind_documented_gauge` | gauge | — | fine |\n"
        "| `hivemind_stale_rows_total` | counter | — | registered nowhere |\n"
    )
    failures, warnings = check_metric_docs.check(package_root=package, doc_path=doc)
    assert any("hivemind_phantom_total" in failure for failure in failures), failures
    assert not any("hivemind_documented_gauge" in failure for failure in failures)
    assert any("hivemind_stale_rows_total" in warning for warning in warnings), warnings
