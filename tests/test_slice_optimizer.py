"""The FULL collaborative Optimizer on a multi-host slice (VERDICT r3 next-round #1):
TWO REAL ``jax.distributed`` processes form ONE mesh and train as ONE swarm peer with
the complete reference semantics — target_batch_size epochs, swarm gradient
averaging, progress tracker, periodic state averaging — in lockstep with a plain
host-resident ``Optimizer`` peer. A fresh slice then joins late and adopts the
swarm's state via the collective download path: the donor tensors must land on BOTH
processes' device shards (reference hivemind/optim/optimizer.py:32-790 semantics).

Only process 0 owns any networking; process 1 asserts it never constructs a DHT.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys, threading, time

proc_id = int(sys.argv[1])
port = sys.argv[2]
dpu_mode = len(sys.argv) > 3 and sys.argv[3] == "dpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=proc_id
)
import numpy as np
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hivemind_tpu.dht import DHT
from hivemind_tpu.optim import Optimizer, SliceOptimizer

devices = np.array(jax.devices()).reshape(8)
mesh = Mesh(devices, ("dp",))

rng = np.random.RandomState(3)
w0 = rng.randn(8, 16).astype(np.float32) * 0.1
b0 = np.zeros(16, np.float32)
params = {
    "w": jax.device_put(w0, NamedSharding(mesh, P("dp"))),
    "b": jax.device_put(b0, NamedSharding(mesh, P())),
}
LR, TARGET = 0.1, 64
opt = optax.sgd(LR)
common_av = dict(target_group_size=2, min_group_size=2,
                 matchmaking_time=2.0, averaging_timeout=40.0)

host_dht = host_opt = None
if proc_id == 0:
    boot = DHT(start=True)
    maddrs = [str(m) for m in boot.get_visible_maddrs()]
    host_dht = DHT(initial_peers=maddrs, start=True)
    host_opt = Optimizer(
        dht=host_dht, run_id="slice_full_opt", params={"w": jnp.asarray(w0), "b": jnp.asarray(b0)},
        optimizer=opt, target_batch_size=TARGET, batch_size_per_step=16, **common_av,
    )
    dht_factory = lambda: boot
else:
    dht_factory = lambda: (_ for _ in ()).throw(
        AssertionError("dht_factory called on a non-network process")
    )

slice_opt = SliceOptimizer(
    mesh=mesh, params=params, optimizer=opt, dht_factory=dht_factory,
    run_id="slice_full_opt", target_batch_size=TARGET, batch_size_per_step=16,
    load_state_timeout=30.0, delay_grad_averaging=dpu_mode,
    **(common_av if proc_id == 0 else {}),
)
if proc_id != 0:
    # the structural claim: followers own NO networking objects at all
    assert slice_opt.dht is None and slice_opt.grad_averager is None
    assert slice_opt.state_averager is None and slice_opt.tracker is None

# deterministic gradients: slice contributes 1.0/2.0, host peer 3.0/4.0 — with
# equal sample weights the swarm average is w:2.0, b:3.0 per epoch, so after E
# epochs BOTH peers must hold exactly w0 - LR*2*E / b0 - LR*3*E (the large-batch
# equivalence the reference promises, optimizer.py:63-69)
g_slice = {
    "w": jax.device_put(np.full((8, 16), 1.0, np.float32), NamedSharding(mesh, P("dp"))),
    "b": jax.device_put(np.full(16, 2.0, np.float32), NamedSharding(mesh, P())),
}
g_host = {"w": jnp.full((8, 16), 3.0), "b": jnp.full(16, 4.0)}

EPOCHS = 2
stop = threading.Event()
def host_loop():
    # the host peer must stop at the SAME epoch as the slice: if it advanced solo,
    # the late joiner below would adopt a further-evolved state than expected
    while not stop.is_set() and host_opt.local_epoch < EPOCHS:
        host_opt.step(g_host, batch_size=16)
        time.sleep(0.25)

host_thread = None
if proc_id == 0:
    host_thread = threading.Thread(target=host_loop, daemon=True)
    host_thread.start()
deadline = time.monotonic() + 240
steps_while_pending = 0
while slice_opt.local_epoch < EPOCHS and time.monotonic() < deadline:
    # count BEFORE stepping: only a step ENTERED with a round already in flight
    # proves overlap (the launching step itself always leaves _pending set)
    entered_pending = slice_opt._pending is not None
    slice_opt.step(g_slice, batch_size=16)
    if entered_pending:
        steps_while_pending += 1
    time.sleep(0.25)
assert slice_opt.local_epoch >= EPOCHS, f"[{proc_id}] stuck at epoch {slice_opt.local_epoch}"
if dpu_mode:
    # drain the last in-flight round so every counted epoch's update landed
    drain = time.monotonic() + 90
    while slice_opt._pending is not None and time.monotonic() < drain:
        slice_opt.step(None)
        time.sleep(0.25)
    assert slice_opt._pending is None, f"[{proc_id}] pending round never adopted"
    # the overlap is real on BOTH processes: training steps ran while a swarm
    # round was in flight (the synchronous mode blocks inside the round)
    assert steps_while_pending >= 1, f"[{proc_id}] no overlap observed"
epochs_done = slice_opt.local_epoch

# weighted-by-samples group averaging (reference semantics — with the r5 grace
# rule a trailing peer transitions EARLY with its actual accumulated weight, so
# per-epoch applied gradients land BETWEEN the two peers' constants rather than
# at the equal-weight midpoint): every epoch's update must sit inside the
# [min(grads), max(grads)] envelope, and both peers must hold the SAME state
lo_w, hi_w = w0 - LR * 3.0 * epochs_done - 5e-3, w0 - LR * 1.0 * epochs_done + 5e-3
lo_b, hi_b = b0 - LR * 4.0 * epochs_done - 5e-3, b0 - LR * 2.0 * epochs_done + 5e-3

def check_shards_range(arr, lo, hi):
    assert arr.addressable_shards, "process holds no shards"
    for shard in arr.addressable_shards:
        data = np.asarray(shard.data)
        assert (data >= lo[shard.index]).all() and (data <= hi[shard.index]).all(), (
            data, lo[shard.index], hi[shard.index]
        )

def check_shards_match(arr, full, atol):
    assert arr.addressable_shards, "process holds no shards"
    for shard in arr.addressable_shards:
        np.testing.assert_allclose(np.asarray(shard.data), full[shard.index], rtol=0, atol=atol)

# every process verifies ITS shards: together both processes cover the arrays
check_shards_range(slice_opt.params["w"], lo_w, hi_w)
check_shards_range(slice_opt.params["b"], lo_b, hi_b)
assert slice_opt.params["w"].sharding.spec == P("dp")
print(f"TRAIN_OK_{proc_id} epochs={epochs_done}", flush=True)

if proc_id == 0:
    # the host peer adopted the same weighted group averages: equal state
    settle = time.monotonic() + 60
    while host_opt.local_epoch < epochs_done and time.monotonic() < settle:
        time.sleep(0.25)
    hw = np.asarray(jax.device_get(host_opt.params["w"]))
    check_shards_match(slice_opt.params["w"], hw, 5e-2)

# ---- late joiner: a FRESH slice (epoch 0) catches up through the tracker and
# adopts a donor's state COLLECTIVELY — the download must land on both
# processes' shards (VERDICT r3 done-bar)
if proc_id == 0:
    fresh_factory = lambda: DHT(initial_peers=maddrs, start=True)
else:
    fresh_factory = lambda: (_ for _ in ()).throw(AssertionError("follower built a DHT"))

fresh = SliceOptimizer(
    mesh=mesh,
    params={
        "w": jax.device_put(np.zeros((8, 16), np.float32), NamedSharding(mesh, P("dp"))),
        "b": jax.device_put(np.zeros(16, np.float32), NamedSharding(mesh, P())),
    },
    optimizer=opt, dht_factory=fresh_factory,
    run_id="slice_full_opt", target_batch_size=TARGET, batch_size_per_step=16,
    load_state_timeout=30.0, **(common_av if proc_id == 0 else {}),
)
deadline = time.monotonic() + 120
while fresh.local_epoch < epochs_done and time.monotonic() < deadline:
    fresh.step(None)  # no grads: pure catch-up through the tracker decision
    time.sleep(0.5)
assert fresh.local_epoch >= epochs_done, f"[{proc_id}] late joiner stuck at {fresh.local_epoch}"
# the joiner adopted the DONOR's state: its shards equal the trained slice's
# (mirrors are refreshed at every transition, and training has stopped)
for name in ("w", "b"):
    donor_full = np.zeros(fresh.params[name].shape, np.float32)
    for shard in slice_opt.params[name].addressable_shards:
        donor_full[shard.index] = np.asarray(shard.data)
    # each process checks ITS joiner shards against ITS donor shards (same mesh
    # layout on both optimizers, so the local shard indices coincide)
    for shard in fresh.params[name].addressable_shards:
        np.testing.assert_allclose(
            np.asarray(shard.data), donor_full[shard.index], rtol=0, atol=5e-3
        )
print(f"JOIN_OK_{proc_id} epoch={fresh.local_epoch}", flush=True)

stop.set()
if host_thread is not None:
    host_thread.join(timeout=60)
fresh.shutdown()
slice_opt.shutdown()
if proc_id == 0:
    host_opt.shutdown(); host_dht.shutdown()
print(f"SLICE_OPT_OK_{proc_id}", flush=True)
"""


def _run_two_process_slice_workers(tmp_path, mode: str = "sync"):
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = str(probe.getsockname()[1])
    script = tmp_path / "slice_opt_worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ, PYTHONPATH=os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if p]
    ))
    return [
        subprocess.Popen(
            [sys.executable, str(script), str(i), port, mode],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for i in range(2)
    ]


def _assert_two_process_workers_ok(workers):
    try:
        for i, worker in enumerate(workers):
            out, _ = worker.communicate(timeout=540)
            assert worker.returncode == 0, f"worker {i} failed:\n{out[-4000:]}"
            assert f"TRAIN_OK_{i}" in out, out[-4000:]
            assert f"JOIN_OK_{i}" in out, out[-4000:]
            assert f"SLICE_OPT_OK_{i}" in out, out[-4000:]
    finally:
        for worker in workers:
            if worker.poll() is None:
                worker.kill()


def test_full_optimizer_on_two_process_slice(tmp_path):
    _assert_two_process_workers_ok(_run_two_process_slice_workers(tmp_path, "sync"))


def test_full_optimizer_on_two_process_slice_dpu(tmp_path):
    """The DELAYED (DPU) path under real multihost collectives: the same
    two-process worker with ``delay_grad_averaging=True`` — the launch/adopt
    lifecycle, the 8-slot decision broadcast, and the catch-up interplay must
    hold with a genuinely separate follower process (the single-process DPU
    tests cannot catch a cross-process collective-ordering divergence). Both
    workers additionally assert steps ran while a round was in flight."""
    _assert_two_process_workers_ok(_run_two_process_slice_workers(tmp_path, "dpu"))


def test_slice_collaborative_example_single_process():
    """The recipe in examples/slice_collaborative_training.py runs end to end on a
    single-process virtual mesh: a solo swarm still advances epochs (no round is
    attempted below 2 peers; local gradients apply) and the script exits cleanly."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the example sets its own device-count flag
    result = subprocess.run(
        [sys.executable, os.path.join(repo, "examples", "slice_collaborative_training.py"),
         "--platform", "cpu", "--devices_per_proc", "4", "--steps", "24",
         "--target_batch_size", "64", "--batch_size", "32", "--dim", "16"],
        capture_output=True, text=True, timeout=420, env=env,
    )
    assert result.returncode == 0, (result.stdout + result.stderr)[-3000:]
    combined = result.stdout + result.stderr
    assert "done: epoch" in combined, combined[-2000:]
    final_epoch = int(combined.rsplit("done: epoch", 1)[1].strip().split()[0])
    assert final_epoch >= 5, combined[-2000:]


def test_slice_optimizer_state_dict_roundtrip():
    """Checkpoint parity with the host Optimizer (reference optimizer.py:719-727):
    state_dict embeds the epoch and every averaged tensor (params + adam mu/nu);
    load_state_dict restores them onto the sharded device state and fast-forwards
    the optax counters, so one identical post-restore epoch update matches the
    original run exactly."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import SliceOptimizer

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    boot = DHT(start=True)
    opt = SliceOptimizer(
        mesh=mesh, params={"w": jax.device_put(np.ones((8, 4), np.float32), sharding)},
        optimizer=optax.adam(0.1), dht_factory=lambda: boot,
        run_id="ckpt_rt", target_batch_size=8, batch_size_per_step=8,
    )
    fresh = None
    try:
        g = {"w": jnp.full((8, 4), 1.0)}
        deadline = time.monotonic() + 90
        while opt.local_epoch < 3 and time.monotonic() < deadline:
            opt.step(g, batch_size=8)
            time.sleep(0.2)
        assert opt.local_epoch >= 3
        checkpoint = opt.state_dict()
        assert checkpoint["epoch"] == opt.local_epoch
        assert len(checkpoint["tensors"]) == 3  # params + adam mu + nu
        trained = np.asarray(jax.device_get(opt.params["w"]))

        # a DIFFERENT run_id: the restore target must not share a swarm with the
        # original — otherwise the original's tracker records can flip the
        # restored peer into the catch-up path mid-comparison (it would download
        # state instead of applying its gradient, making the adam assertion
        # vacuous) and 2-peer trackers would try real averaging rounds
        fresh = SliceOptimizer(
            mesh=mesh, params={"w": jax.device_put(np.zeros((8, 4), np.float32), sharding)},
            optimizer=optax.adam(0.1),
            dht_factory=lambda: DHT(
                initial_peers=[str(m) for m in boot.get_visible_maddrs()], start=True
            ),
            run_id="ckpt_rt_restored", target_batch_size=8, batch_size_per_step=8,
        )
        fresh.load_state_dict(checkpoint)
        assert fresh.local_epoch == checkpoint["epoch"]
        np.testing.assert_allclose(
            np.asarray(jax.device_get(fresh.params["w"])), trained, atol=1e-6
        )
        # adam statistics restored: one identical (solo, local-gradient) epoch
        # update on both sides must produce identical params. Exactly ONE
        # transition each: if step() already fired it via the tracker, forcing a
        # second would apply a spurious zero-grad adam update
        for instance in (opt, fresh):
            before = instance.local_epoch
            instance.step(g, batch_size=8)
            if instance.local_epoch == before:
                instance.force_epoch_transition()
        np.testing.assert_allclose(
            np.asarray(jax.device_get(fresh.params["w"])),
            np.asarray(jax.device_get(opt.params["w"])), atol=1e-6,
        )
    finally:
        if fresh is not None:
            fresh.shutdown()
        opt.shutdown()


def test_slice_optimizer_with_powersgd_interoperates_with_host_peer():
    """PowerSGD gradient compression on the slice tier: a SliceOptimizer with a
    PowerSGDGradientAverager factory trains in lockstep with a host Optimizer
    peer using the same factory. Constant gradients are exactly rank-1, so the
    factorized rounds are lossless and both peers must land on the exact
    large-batch average — and on each other."""
    import threading
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import Optimizer, PowerSGDGradientAverager, SliceOptimizer

    import functools

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    LR, TARGET = 0.1, 32
    # a partial (not a lambda) lets SliceOptimizer see the class and skip the
    # host accumulator allocation (its accumulation lives on device)
    factory = functools.partial(PowerSGDGradientAverager, averager_rank=1)

    boot = DHT(start=True)
    slice_opt = SliceOptimizer(
        mesh=mesh, params={"w": jax.device_put(np.zeros((8, 16), np.float32), sharding)},
        optimizer=optax.sgd(LR), dht_factory=lambda: boot,
        run_id="psgd_slice", target_batch_size=TARGET, batch_size_per_step=8,
        target_group_size=2, matchmaking_time=1.5, averaging_timeout=40.0,
        grad_averager_factory=factory,
    )
    q_seed = np.array(slice_opt.grad_averager._qs[0])  # warm-start Q before any round
    host_dht = DHT(initial_peers=[str(m) for m in boot.get_visible_maddrs()], start=True)
    host_opt = Optimizer(
        dht=host_dht, run_id="psgd_slice", params={"w": jnp.zeros((8, 16))},
        optimizer=optax.sgd(LR), target_batch_size=TARGET, batch_size_per_step=8,
        target_group_size=2, matchmaking_time=1.5, averaging_timeout=40.0,
        grad_averager_factory=factory,
    )
    g_slice = {"w": jax.device_put(np.full((8, 16), 2.0, np.float32), sharding)}
    g_host = {"w": jnp.full((8, 16), 4.0)}
    EPOCHS = 2
    stop = threading.Event()

    def host_loop():
        while not stop.is_set() and host_opt.local_epoch < EPOCHS:
            host_opt.step(g_host, batch_size=8)
            time.sleep(0.2)

    thread = threading.Thread(target=host_loop, daemon=True)
    thread.start()
    try:
        deadline = time.monotonic() + 180
        while slice_opt.local_epoch < EPOCHS and time.monotonic() < deadline:
            slice_opt.step(g_slice, batch_size=8)
            time.sleep(0.2)
        assert slice_opt.local_epoch >= EPOCHS, f"stuck at {slice_opt.local_epoch}"
        epochs = slice_opt.local_epoch
        # the slice loop exits the moment IT transitions; let the host finish its
        # own epoch-2 transition before comparing (its thread stops itself there)
        settle = time.monotonic() + 60
        while host_opt.local_epoch < epochs and time.monotonic() < settle:
            time.sleep(0.2)
        stop.set()
        thread.join(timeout=60)
        assert host_opt.local_epoch >= epochs, f"host stuck at {host_opt.local_epoch}"
        # the device-side accumulation path really skipped the host buffers
        assert slice_opt.grad_averager._grad_accumulators is None
        sw = np.asarray(jax.device_get(slice_opt.params["w"]))
        hw = np.asarray(jax.device_get(host_opt.params["w"]))
        # both peers ADOPT the same factorized group average every epoch, so they
        # must agree exactly — regardless of how the sample split landed; the
        # value itself sits between the all-slice and all-host extremes (the
        # weighted mean of grads 2.0 and 4.0)
        np.testing.assert_allclose(sw, hw, atol=5e-3)
        assert (-LR * 4.0 * epochs - 5e-3) <= sw[0, 0] <= (-LR * 2.0 * epochs + 5e-3), sw[0, 0]
        # the compressed rounds really happened: a successful P/Q round replaces
        # the warm-start Q (seeded 0xC0FFEE) with the orthogonalized average
        assert not np.allclose(slice_opt.grad_averager._qs[0], q_seed), (
            "warm-start Q unchanged: no factorized round ever completed"
        )
    finally:
        stop.set()
        thread.join(timeout=60)
        slice_opt.shutdown()
        host_opt.shutdown()
        host_dht.shutdown()


def test_delay_grad_averaging_overlaps_training():
    """The slice-tier DPU analog (VERDICT r4 next-round #1): with
    ``delay_grad_averaging=True`` and a deliberately SLOW swarm round (2 s of
    injected latency inside the averager), the slice (a) keeps stepping while the
    round is in flight — synchronous mode would complete zero steps there — and
    (b) still reaches epoch lockstep with a host Optimizer peer on the exact
    same group averages: final params equal across peers and bounded by the
    all-slice / all-host gradient extremes (one-epoch-stale adoption loses no
    gradients and double-applies none)."""
    import threading
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hivemind_tpu.averaging.averager import DecentralizedAverager
    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import Optimizer, SliceOptimizer

    ROUND_LATENCY = 2.0

    class SlowAverager(DecentralizedAverager):
        def step(self, *args, wait=True, **kwargs):
            if wait:  # only the blocking round call, not schedule-style dispatch
                time.sleep(ROUND_LATENCY)
            return super().step(*args, wait=wait, **kwargs)

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    LR, TARGET = 0.1, 256
    boot = DHT(start=True)
    slice_opt = SliceOptimizer(
        mesh=mesh, params={"w": jax.device_put(np.zeros((8, 16), np.float32), sharding)},
        optimizer=optax.sgd(LR), dht_factory=lambda: boot,
        run_id="dpu_slice", target_batch_size=TARGET, batch_size_per_step=8,
        target_group_size=2, matchmaking_time=4.0, averaging_timeout=60.0,
        delay_grad_averaging=True, grad_averager_factory=SlowAverager,
    )
    # force every round through the (slowed) blocking step call: pre-scheduled
    # controls would bypass the injection and blur the A/B
    slice_opt._maybe_schedule_gradient_averaging = lambda: None
    host_dht = DHT(initial_peers=[str(m) for m in boot.get_visible_maddrs()], start=True)
    host_opt = Optimizer(
        dht=host_dht, run_id="dpu_slice", params={"w": jnp.zeros((8, 16))},
        optimizer=optax.sgd(LR), target_batch_size=TARGET, batch_size_per_step=8,
        target_group_size=2, matchmaking_time=4.0, averaging_timeout=60.0,
    )
    g_slice = {"w": jax.device_put(np.full((8, 16), 1.0, np.float32), sharding)}
    g_host = {"w": jnp.full((8, 16), 3.0)}
    EPOCHS = 2
    stop = threading.Event()

    def host_loop():
        while not stop.is_set() and host_opt.local_epoch < EPOCHS:
            host_opt.step(g_host, batch_size=8)
            time.sleep(0.1)

    thread = threading.Thread(target=host_loop, daemon=True)
    thread.start()
    steps_while_pending = 0
    try:
        deadline = time.monotonic() + 240
        while slice_opt.local_epoch < EPOCHS and time.monotonic() < deadline:
            # count BEFORE stepping: only a step ENTERED with a round already in
            # flight proves overlap (the launch itself always sets _pending)
            entered_pending = slice_opt._pending is not None
            slice_opt.step(g_slice, batch_size=8)
            if entered_pending:
                steps_while_pending += 1
            time.sleep(0.02)
        assert slice_opt.local_epoch >= EPOCHS, f"stuck at {slice_opt.local_epoch}"
        epochs = slice_opt.local_epoch
        # (a) the overlap: training steps completed while a swarm round was in
        # flight (in synchronous mode this count is structurally zero — step()
        # blocks inside the round)
        assert steps_while_pending >= 3, steps_while_pending
        # the epoch advances at LAUNCH (reference DPU semantics); drain the last
        # in-flight round so every counted epoch's update has landed
        drain = time.monotonic() + 120
        while slice_opt._pending is not None and time.monotonic() < drain:
            slice_opt.step(None)
            time.sleep(0.1)
        assert slice_opt._pending is None, "pending round never completed"
        settle = time.monotonic() + 90
        while host_opt.local_epoch < epochs and time.monotonic() < settle:
            time.sleep(0.2)
        stop.set()
        thread.join(timeout=60)
        assert host_opt.local_epoch >= epochs, f"host stuck at {host_opt.local_epoch}"
        # (b) both peers hold the SAME adopted group averages
        sw = np.asarray(jax.device_get(slice_opt.params["w"]))
        hw = np.asarray(jax.device_get(host_opt.params["w"]))
        np.testing.assert_allclose(sw, hw, atol=5e-3)
        assert (-LR * 3.0 * epochs - 5e-3) <= sw[0, 0] <= (-LR * 1.0 * epochs + 5e-3), sw[0, 0]
    finally:
        stop.set()
        thread.join(timeout=60)
        slice_opt.shutdown()
        host_opt.shutdown()
        host_dht.shutdown()


def test_broadcast_thinning_preserves_lockstep_and_transitions():
    """Per-step broadcast thinning (VERDICT r4 next-round #8): far from the epoch
    boundary, process 0 announces skip counts and subsequent steps run ZERO
    collectives — strictly fewer broadcasts than steps — yet the epoch
    transition still fires and applies the right update. Near the boundary the
    skip shrinks to 0 (the pre-scheduling window is honored)."""
    import time

    import jax
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import hivemind_tpu.optim.slice_optimizer as slice_mod
    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import SliceOptimizer

    broadcasts = {"count": 0}
    real_broadcast = slice_mod._broadcast

    def counting_broadcast(value):
        broadcasts["count"] += 1
        return real_broadcast(value)

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    opt = SliceOptimizer(
        mesh=mesh, params={"w": jax.device_put(np.zeros((8, 4), np.float32), sharding)},
        optimizer=optax.sgd(0.1), dht_factory=lambda: DHT(start=True),
        run_id="thinned_bcast", target_batch_size=512, batch_size_per_step=8,
        max_broadcast_skip=4,
    )
    slice_mod._broadcast = counting_broadcast
    g = {"w": jax.device_put(np.ones((8, 4), np.float32), sharding)}
    try:
        steps = 0
        deadline = time.monotonic() + 120
        while opt.local_epoch < 1 and time.monotonic() < deadline:
            opt.step(g, batch_size=8)
            steps += 1
            time.sleep(0.05)
        assert opt.local_epoch >= 1, "no epoch transition under thinning"
        # decision broadcasts are a strict subset of steps (the transition itself
        # adds non-decision collectives, so compare against a thinning margin)
        assert broadcasts["count"] < steps, (broadcasts["count"], steps)
        assert opt._step_time_ema is not None
        # the solo local-gradient update really applied
        w = np.asarray(jax.device_get(opt.params["w"]))
        np.testing.assert_allclose(w, -0.1 * 1.0 * opt.local_epoch, atol=1e-5)
    finally:
        slice_mod._broadcast = real_broadcast
        opt.shutdown()


@pytest.mark.slow  # ~60 s; state_dict round-tripping stays covered in ~4 s by
# test_slice_optimizer_state_dict_roundtrip and
# test_optimizer_dpu.py::test_state_dict_roundtrip_with_schedule_replay
def test_load_state_dict_discards_pending_delayed_round():
    """A checkpoint restore during an in-flight delayed round must DISCARD the
    round: its staged gradients were computed against the replaced state, and
    landing them on the restored params would silently corrupt the checkpoint
    (review finding on the r5 DPU work). The restore wins; the next steps train
    from exactly the checkpoint."""
    import time

    import jax
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import SliceOptimizer
    from hivemind_tpu.optim.progress_tracker import ProgressTracker

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    TARGET = 16
    boot = DHT(start=True)
    opt = SliceOptimizer(
        mesh=mesh, params={"w": jax.device_put(np.zeros((8, 4), np.float32), sharding)},
        optimizer=optax.sgd(0.1), dht_factory=lambda: boot,
        run_id="restore_vs_pending", target_batch_size=TARGET, batch_size_per_step=8,
        delay_grad_averaging=True, matchmaking_time=1.0, averaging_timeout=30.0,
    )
    ghost_dht = DHT(initial_peers=[str(m) for m in boot.get_visible_maddrs()], start=True)
    ghost = ProgressTracker(ghost_dht, "restore_vs_pending", TARGET)
    try:
        checkpoint = opt.state_dict()  # the all-zeros epoch-0 state
        ghost.report_local_progress(0, TARGET)  # num_peers=2: delayed rounds engage
        g = {"w": jax.device_put(np.ones((8, 4), np.float32), sharding)}
        deadline = time.monotonic() + 60
        while opt._pending is None and time.monotonic() < deadline:
            opt.step(g, batch_size=8)
            time.sleep(0.1)
        assert opt._pending is not None, "no delayed round ever launched"

        opt.load_state_dict(checkpoint)
        assert opt._pending is None, "restore left the stale round pending"
        assert opt.local_epoch == checkpoint["epoch"]
        np.testing.assert_allclose(
            np.asarray(jax.device_get(opt.params["w"])), 0.0, atol=1e-6
        )
        # the next step must NOT adopt ghost-round leftovers onto the restore
        opt.step(None)
        np.testing.assert_allclose(
            np.asarray(jax.device_get(opt.params["w"])), 0.0, atol=1e-6
        )
    finally:
        ghost.shutdown()
        ghost_dht.shutdown()
        opt.shutdown()


def test_thinned_steps_defer_network_errors_to_next_broadcast():
    """An error in process 0's networking DURING a skipped (collective-free) step
    must not raise there — that would desync the skip countdown across processes
    — but at the NEXT broadcast step, via the error-flagged decision vector."""
    import jax
    import numpy as np
    import optax
    import pytest
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import SliceOptimizer

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    opt = SliceOptimizer(
        mesh=mesh, params={"w": jax.device_put(np.zeros((8, 4), np.float32), sharding)},
        optimizer=optax.sgd(0.1), dht_factory=lambda: DHT(start=True),
        run_id="thinned_defer", target_batch_size=1 << 30, batch_size_per_step=1,
        max_broadcast_skip=4,
    )
    g = {"w": jax.device_put(np.ones((8, 4), np.float32), sharding)}
    try:
        deadline_steps = 200
        while opt._skip_remaining == 0 and deadline_steps:
            opt.step(g, batch_size=1)
            deadline_steps -= 1
        assert opt._skip_remaining > 0, "thinning never engaged"

        def boom(*args, **kwargs):
            raise OSError("injected during a skipped step")

        opt.tracker.report_local_progress = boom
        skipped_without_raise = 0
        with pytest.raises(OSError, match="injected during a skipped step"):
            for _ in range(opt._skip_remaining + 1):
                before = opt._skip_remaining
                opt.step(g, batch_size=1)
                if before > 0:
                    skipped_without_raise += 1  # skipped steps swallow + defer
        assert skipped_without_raise >= 1
    finally:
        opt.shutdown()


def test_network_process_failure_raises_in_lockstep_not_hangs():
    """Advisor r4 medium finding: if process 0's networking raises inside step()'s
    decision phase (DHT store failure, tracker shutdown), it must STILL broadcast
    — with the error flag set — so followers raise in lockstep instead of parking
    forever in the collective. On one process we can assert the p0 half: the
    original exception propagates (after the sentinel broadcast) rather than
    being swallowed or skipping the broadcast."""
    import jax
    import numpy as np
    import optax
    import pytest
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import SliceOptimizer

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    opt = SliceOptimizer(
        mesh=mesh, params={"w": jax.device_put(np.ones((8, 4), np.float32), sharding)},
        optimizer=optax.sgd(0.1), dht_factory=lambda: DHT(start=True),
        run_id="sentinel_bcast", target_batch_size=64, batch_size_per_step=4,
    )
    try:
        g = {"w": jax.device_put(np.ones((8, 4), np.float32), sharding)}
        opt.step(g, batch_size=4)  # sanity: a healthy step works

        def boom(*args, **kwargs):
            raise OSError("injected: dht store failed")

        opt.tracker.report_local_progress = boom
        with pytest.raises(OSError, match="injected: dht store failed"):
            opt.step(g, batch_size=4)
    finally:
        opt.shutdown()


def test_one_swarm_all_four_roles():
    """The reference's heterogeneity story end-to-end WITH a slice in the group
    (VERDICT r4 next-round #5; reference allreduce.py:26-29 + optimizer.py:147-148):
    one run_id carries a SliceOptimizer peer, a host NODE, a firewalled CLIENT,
    and an AUX reducer. All four advance epochs in lockstep; the client joins
    rounds send-only (its averagers run client_mode — never dialable, never a
    leader); the aux peer owns no data (no params, weight-0 contributions,
    schema bootstrapped from the swarm)."""
    import threading
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import Optimizer, SliceOptimizer

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    LR, TARGET, EPOCHS = 0.1, 72, 2
    common = dict(
        run_id="four_roles", target_batch_size=TARGET,
        target_group_size=4, matchmaking_time=2.5, averaging_timeout=40.0,
    )
    boot = DHT(start=True)
    maddrs = [str(m) for m in boot.get_visible_maddrs()]
    slice_opt = SliceOptimizer(
        mesh=mesh, params={"w": jax.device_put(np.zeros((8, 16), np.float32), sharding)},
        optimizer=optax.sgd(LR), dht_factory=lambda: boot,
        batch_size_per_step=8, **common,
    )
    node_dht = DHT(initial_peers=maddrs, start=True)
    node_opt = Optimizer(
        dht=node_dht, params={"w": jnp.zeros((8, 16))}, optimizer=optax.sgd(LR),
        batch_size_per_step=8, **common,
    )
    client_dht = DHT(initial_peers=maddrs, start=True)
    client_opt = Optimizer(
        dht=client_dht, params={"w": jnp.zeros((8, 16))}, optimizer=optax.sgd(LR),
        batch_size_per_step=8, client_mode=True, **common,
    )
    aux_dht = DHT(initial_peers=maddrs, start=True)
    aux_opt = Optimizer(dht=aux_dht, load_state_timeout=60.0, **common, auxiliary=True)

    # per-role structure: the client's averager is client_mode (sends-only, never
    # a leader/dialable); the aux peer owns NO model state of its own
    assert client_opt.grad_averager.client_mode
    assert aux_opt.auxiliary and aux_opt.state_averager is None  # owns no model state
    with aux_opt.grad_averager.get_tensors() as aux_tensors:
        assert sorted(tuple(t.shape) for t in aux_tensors) == [(8, 16)]  # bootstrapped schema

    stop = threading.Event()
    g_node = {"w": jnp.full((8, 16), 2.0)}
    g_client = {"w": jnp.full((8, 16), 3.0)}

    def data_loop(opt, grads):
        while not stop.is_set() and opt.local_epoch < EPOCHS:
            opt.step(grads, batch_size=8)
            time.sleep(0.15)

    def aux_loop():
        while not stop.is_set() and aux_opt.local_epoch < EPOCHS:
            aux_opt.step()
            time.sleep(0.2)

    threads = [
        threading.Thread(target=data_loop, args=(node_opt, g_node), daemon=True),
        threading.Thread(target=data_loop, args=(client_opt, g_client), daemon=True),
        threading.Thread(target=aux_loop, daemon=True),
    ]
    for thread in threads:
        thread.start()
    g_slice = {"w": jax.device_put(np.full((8, 16), 1.0, np.float32), sharding)}
    try:
        deadline = time.monotonic() + 240
        while slice_opt.local_epoch < EPOCHS and time.monotonic() < deadline:
            slice_opt.step(g_slice, batch_size=8)
            time.sleep(0.1)
        assert slice_opt.local_epoch >= EPOCHS, f"slice stuck at {slice_opt.local_epoch}"
        # every role advances with the swarm (the aux's epoch is the tracker's)
        settle = time.monotonic() + 120
        peers = {"node": node_opt, "client": client_opt, "aux": aux_opt}
        while time.monotonic() < settle and any(
            p.local_epoch < EPOCHS for p in peers.values()
        ):
            time.sleep(0.2)
        for name, peer in peers.items():
            assert peer.local_epoch >= EPOCHS, f"{name} stuck at {peer.local_epoch}"
        for peer in (slice_opt, node_opt, client_opt):
            for leaf in jax.tree_util.tree_leaves(peer.params):
                assert np.isfinite(np.asarray(jax.device_get(leaf))).all()
    finally:
        stop.set()
        for thread in threads:
            thread.join(timeout=60)
        slice_opt.shutdown()
        node_opt.shutdown()
        client_opt.shutdown()
        aux_opt.shutdown()
        for dht in (node_dht, client_dht, aux_dht):
            dht.shutdown()


def test_slice_degrades_to_local_grads_and_recovers_on_groupmate_churn():
    """Churn for the slice tier (VERDICT r4 next-round #6, reference bar
    tests/test_allreduce_fault_tolerance.py:22-120): a groupmate that reports
    progress but VANISHES before the round leaves the slice's matchmaking empty —
    the epoch still transitions on local gradients and the chronic counter moves;
    when a real host peer replaces it, the next round succeeds and the counter
    resets."""
    import threading
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import Optimizer, SliceOptimizer
    from hivemind_tpu.optim.progress_tracker import ProgressTracker

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    LR, TARGET = 0.1, 32
    boot = DHT(start=True)
    slice_opt = SliceOptimizer(
        mesh=mesh, params={"w": jax.device_put(np.zeros((8, 16), np.float32), sharding)},
        optimizer=optax.sgd(LR), dht_factory=lambda: boot,
        run_id="churn_slice", target_batch_size=TARGET, batch_size_per_step=8,
        target_group_size=2, matchmaking_time=1.0, averaging_timeout=10.0,
    )
    ghost_dht = DHT(initial_peers=[str(m) for m in boot.get_visible_maddrs()], start=True)
    ghost = ProgressTracker(ghost_dht, "churn_slice", TARGET)
    g_slice = {"w": jax.device_put(np.full((8, 16), 1.0, np.float32), sharding)}
    host_opt = host_dht = None
    try:
        # phase 1: the ghost reports a full batch of progress, then never shows up
        # for the round — the slice must transition on LOCAL gradients
        ghost.report_local_progress(0, TARGET)
        deadline = time.monotonic() + 90
        while slice_opt.local_epoch < 1 and time.monotonic() < deadline:
            slice_opt.step(g_slice, batch_size=8)
            time.sleep(0.1)
        assert slice_opt.local_epoch >= 1, "no epoch transition after groupmate vanished"
        assert slice_opt.consecutive_failed_averaging_rounds >= 1, (
            "the failed round must move the chronic counter"
        )
        w = np.asarray(jax.device_get(slice_opt.params["w"]))
        np.testing.assert_allclose(w, -LR * 1.0, atol=1e-5)  # exactly the local update

        # phase 2: a real host peer replaces the ghost; the next round succeeds
        ghost.shutdown()
        host_dht = DHT(initial_peers=[str(m) for m in boot.get_visible_maddrs()], start=True)
        host_opt = Optimizer(
            dht=host_dht, run_id="churn_slice", params={"w": jnp.asarray(w)},
            optimizer=optax.sgd(LR), target_batch_size=TARGET, batch_size_per_step=8,
            target_group_size=2, matchmaking_time=1.5, averaging_timeout=30.0,
        )
        target_epoch = slice_opt.local_epoch + 1
        stop = threading.Event()
        g_host = {"w": jnp.full((8, 16), 3.0)}

        def host_loop():
            while not stop.is_set() and host_opt.local_epoch < target_epoch + 5:
                host_opt.step(g_host, batch_size=8)
                time.sleep(0.15)

        thread = threading.Thread(target=host_loop, daemon=True)
        thread.start()
        # run until a round actually SUCCEEDS (counter resets); allow a couple of
        # epochs of slack for mistimed first windows on one contended core
        deadline = time.monotonic() + 150
        while time.monotonic() < deadline and not (
            slice_opt.local_epoch >= target_epoch
            and slice_opt.consecutive_failed_averaging_rounds == 0
        ):
            slice_opt.step(g_slice, batch_size=8)
            time.sleep(0.1)
        stop.set()
        thread.join(timeout=60)
        assert slice_opt.local_epoch >= target_epoch, "no recovery round"
        assert slice_opt.consecutive_failed_averaging_rounds == 0, (
            "a successful round must reset the chronic counter"
        )
        # the successful rounds really averaged: with the host's larger gradient
        # (3.0 vs 1.0) in the mix, the slice moved FURTHER than local-only would
        w2 = np.asarray(jax.device_get(slice_opt.params["w"]))
        local_only = w - LR * 1.0 * (slice_opt.local_epoch - 1)
        assert w2[0, 0] < local_only[0, 0] - 1e-4, (w2[0, 0], local_only[0, 0])
    finally:
        import contextlib

        with contextlib.suppress(Exception):
            ghost.shutdown()
        if host_opt is not None:
            host_opt.shutdown()
        if host_dht is not None:
            host_dht.shutdown()
        slice_opt.shutdown()


def test_slice_survives_groupmate_dying_mid_allreduce():
    """A host groupmate that dies MID-ALLREDUCE (sends one part, then its sends
    abort — Fault.FAIL_SENDING from the fault matrix, now armed through the
    first-class chaos engine): the slice's epoch still transitions without
    hanging, parameters stay finite, and after the faulty peer heals (rules
    cleared) a later round completes with both peers converging."""
    import threading
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from test_allreduce_fault_tolerance import Fault, arm_fault

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import Optimizer, SliceOptimizer
    from hivemind_tpu.resilience import CHAOS

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    LR, TARGET = 0.1, 32
    boot = DHT(start=True)
    # every averager in a group must agree on part_size_bytes (partitioning is
    # part of the wire contract); 64-byte parts make FAIL_SENDING strike
    # mid-stream rather than after the whole tensor
    slice_opt = SliceOptimizer(
        mesh=mesh, params={"w": jax.device_put(np.zeros((8, 16), np.float32), sharding)},
        optimizer=optax.sgd(LR), dht_factory=lambda: boot,
        run_id="midreduce_slice", target_batch_size=TARGET, batch_size_per_step=8,
        target_group_size=2, matchmaking_time=1.5, averaging_timeout=20.0,
        part_size_bytes=64, sender_timeout=3.0, reducer_timeout=6.0,
    )
    host_dht = DHT(initial_peers=[str(m) for m in boot.get_visible_maddrs()], start=True)
    host_opt = Optimizer(
        dht=host_dht, run_id="midreduce_slice", params={"w": jnp.zeros((8, 16))},
        optimizer=optax.sgd(LR), target_batch_size=TARGET, batch_size_per_step=8,
        target_group_size=2, matchmaking_time=1.5, averaging_timeout=20.0,
        grad_averager_opts=dict(sender_timeout=3.0, reducer_timeout=6.0, part_size_bytes=64),
        state_averager_opts=dict(part_size_bytes=64, sender_timeout=3.0, reducer_timeout=6.0),
    )
    # the host peer's sends abort after the first part (scoped to its peer id:
    # the slice's own traffic through the shared engine stays clean)
    arm_fault(Fault.FAIL_SENDING, str(host_dht.peer_id))
    g_slice = {"w": jax.device_put(np.full((8, 16), 1.0, np.float32), sharding)}
    g_host = {"w": jnp.full((8, 16), 3.0)}
    stop = threading.Event()
    EPOCHS = 3

    def host_loop():
        while not stop.is_set() and host_opt.local_epoch < EPOCHS + 5:
            host_opt.step(g_host, batch_size=8)
            time.sleep(0.15)

    thread = threading.Thread(target=host_loop, daemon=True)
    thread.start()
    try:
        # epoch 1 under a mid-allreduce death: must complete, not hang
        deadline = time.monotonic() + 120
        while slice_opt.local_epoch < 1 and time.monotonic() < deadline:
            slice_opt.step(g_slice, batch_size=8)
            time.sleep(0.1)
        assert slice_opt.local_epoch >= 1, "slice hung on a groupmate dying mid-allreduce"
        w1 = np.asarray(jax.device_get(slice_opt.params["w"]))
        assert np.isfinite(w1).all()

        # the groupmate heals: run until a post-heal round SUCCEEDS (the counter
        # resets), allowing a couple of epochs of slack for mistimed windows
        CHAOS.clear()
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline and not (
            slice_opt.local_epoch >= EPOCHS
            and slice_opt.consecutive_failed_averaging_rounds == 0
        ):
            slice_opt.step(g_slice, batch_size=8)
            time.sleep(0.1)
        assert slice_opt.local_epoch >= EPOCHS, f"stuck at {slice_opt.local_epoch}"
        settle = time.monotonic() + 60
        while host_opt.local_epoch < slice_opt.local_epoch and time.monotonic() < settle:
            time.sleep(0.2)
        stop.set()
        thread.join(timeout=60)
        assert slice_opt.consecutive_failed_averaging_rounds == 0
        sw = np.asarray(jax.device_get(slice_opt.params["w"]))
        hw = np.asarray(jax.device_get(host_opt.params["w"]))
        np.testing.assert_allclose(sw, hw, atol=5e-3)
    finally:
        CHAOS.clear()
        stop.set()
        thread.join(timeout=60)
        slice_opt.shutdown()
        host_opt.shutdown()
        host_dht.shutdown()


def test_slice_state_download_fails_over_when_donor_dies_mid_stream():
    """The state donor dies mid-download while a slice catches up: the truncated
    stream (fewer tensors than the schema) must fail over IN-LOOP to the next
    donor — the slice adopts the healthy donor's state at the advertised epoch,
    never a half-written one (VERDICT r4 next-round #6, second scenario)."""
    import time

    import jax
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hivemind_tpu.averaging.averager import DecentralizedAverager
    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import SliceOptimizer
    from hivemind_tpu.optim.progress_tracker import ProgressTracker

    DONOR_EPOCH = 3

    class HealthyDonor(DecentralizedAverager):
        async def _get_current_state(self):
            return {"epoch": DONOR_EPOCH}, self._snapshot_tensors()

    class TruncatingDonor(DecentralizedAverager):
        async def _get_current_state(self):
            # dies after streaming the first tensor: a clean early end-of-stream,
            # exactly what a SIGKILLed donor's socket close looks like post-frame
            return {"epoch": DONOR_EPOCH}, self._snapshot_tensors()[:1]

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    TARGET = 32
    boot = DHT(start=True)
    params = {
        "b": jax.device_put(np.zeros(16, np.float32), NamedSharding(mesh, P())),
        "w": jax.device_put(np.zeros((8, 16), np.float32), sharding),
    }
    slice_opt = SliceOptimizer(
        mesh=mesh, params=params, optimizer=optax.sgd(0.1), dht_factory=lambda: boot,
        run_id="donor_churn", target_batch_size=TARGET, batch_size_per_step=8,
        load_state_timeout=20.0,
    )
    state_templates = [np.zeros(leaf.shape, np.float32) for leaf in slice_opt._state_leaves()]
    donor_values = [np.full(t.shape, 7.0, np.float32) for t in state_templates]

    faulty_dht = DHT(initial_peers=[str(m) for m in boot.get_visible_maddrs()], start=True)
    faulty = TruncatingDonor(
        [np.array(v) for v in donor_values], faulty_dht,
        prefix="donor_churn_state", start=True, declare_state_period=1.0,
    )
    healthy_dht = DHT(initial_peers=[str(m) for m in boot.get_visible_maddrs()], start=True)
    healthy = HealthyDonor(
        [np.array(v) for v in donor_values], healthy_dht,
        prefix="donor_churn_state", start=True, declare_state_period=1.0,
    )
    # the faulty donor advertises the HIGHER priority, so it is tried first
    faulty.state_sharing_priority = DONOR_EPOCH + 5
    healthy.state_sharing_priority = DONOR_EPOCH
    ghost = ProgressTracker(healthy_dht, "donor_churn", TARGET)
    try:
        ghost.report_local_progress(DONOR_EPOCH, 0)
        time.sleep(3.0)  # let the re-declared priorities + progress land in the DHT
        g = {k: jax.device_put(np.ones(v.shape, np.float32), v.sharding) for k, v in params.items()}
        deadline = time.monotonic() + 90
        while slice_opt.local_epoch < DONOR_EPOCH and time.monotonic() < deadline:
            slice_opt.step(g, batch_size=8)
            time.sleep(0.2)
        assert slice_opt.local_epoch == DONOR_EPOCH, slice_opt.local_epoch
        # the adopted tensors are the HEALTHY donor's, not a truncated mix
        for leaf in jax.tree_util.tree_leaves(slice_opt.params):
            np.testing.assert_allclose(np.asarray(jax.device_get(leaf)), 7.0, atol=1e-5)
    finally:
        ghost.shutdown()
        faulty.shutdown()
        healthy.shutdown()
        faulty_dht.shutdown()
        healthy_dht.shutdown()
        slice_opt.shutdown()


def test_slice_chronic_failure_counter_and_backoff():
    """Host-Optimizer parity (optimizer.py:100-136): consecutive failed swarm
    rounds escalate to chronic failure, matchmaking lead time backs off
    exponentially (capped 8x), pre-scheduling is suppressed while chronic, and
    one success resets everything. Pure unit math — no network."""
    from hivemind_tpu.optim import SliceOptimizer

    opt = SliceOptimizer.__new__(SliceOptimizer)
    opt.matchmaking_time = 2.0
    opt.chronic_failure_threshold = 3
    opt._consecutive_failed_rounds = 0
    opt.is_network_process = True

    assert not opt.chronic_averaging_failure
    assert opt._matchmaking_delay() == 2.0
    opt._record_round_outcome(None)  # solo swarm: neither failure nor recovery
    assert opt.consecutive_failed_averaging_rounds == 0

    for _ in range(3):
        opt._record_round_outcome(False)
    assert opt.chronic_averaging_failure
    assert opt._matchmaking_delay() == 4.0  # 2.0 * 2^1
    opt._record_round_outcome(False)
    assert opt._matchmaking_delay() == 8.0
    for _ in range(10):
        opt._record_round_outcome(False)
    assert opt._matchmaking_delay() == 16.0  # capped at 8x

    opt._record_round_outcome(True)  # recovery resets
    assert opt.consecutive_failed_averaging_rounds == 0
    assert not opt.chronic_averaging_failure
    assert opt._matchmaking_delay() == 2.0
