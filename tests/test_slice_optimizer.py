"""The FULL collaborative Optimizer on a multi-host slice (VERDICT r3 next-round #1):
TWO REAL ``jax.distributed`` processes form ONE mesh and train as ONE swarm peer with
the complete reference semantics — target_batch_size epochs, swarm gradient
averaging, progress tracker, periodic state averaging — in lockstep with a plain
host-resident ``Optimizer`` peer. A fresh slice then joins late and adopts the
swarm's state via the collective download path: the donor tensors must land on BOTH
processes' device shards (reference hivemind/optim/optimizer.py:32-790 semantics).

Only process 0 owns any networking; process 1 asserts it never constructs a DHT.
"""

import os
import socket
import subprocess
import sys

_WORKER = r"""
import os, sys, threading, time
proc_id = int(sys.argv[1])
port = sys.argv[2]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=proc_id
)
import numpy as np
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hivemind_tpu.dht import DHT
from hivemind_tpu.optim import Optimizer, SliceOptimizer

devices = np.array(jax.devices()).reshape(8)
mesh = Mesh(devices, ("dp",))

rng = np.random.RandomState(3)
w0 = rng.randn(8, 16).astype(np.float32) * 0.1
b0 = np.zeros(16, np.float32)
params = {
    "w": jax.device_put(w0, NamedSharding(mesh, P("dp"))),
    "b": jax.device_put(b0, NamedSharding(mesh, P())),
}
LR, TARGET = 0.1, 64
opt = optax.sgd(LR)
common_av = dict(target_group_size=2, min_group_size=2,
                 matchmaking_time=2.0, averaging_timeout=40.0)

host_dht = host_opt = None
if proc_id == 0:
    boot = DHT(start=True)
    maddrs = [str(m) for m in boot.get_visible_maddrs()]
    host_dht = DHT(initial_peers=maddrs, start=True)
    host_opt = Optimizer(
        dht=host_dht, run_id="slice_full_opt", params={"w": jnp.asarray(w0), "b": jnp.asarray(b0)},
        optimizer=opt, target_batch_size=TARGET, batch_size_per_step=16, **common_av,
    )
    dht_factory = lambda: boot
else:
    dht_factory = lambda: (_ for _ in ()).throw(
        AssertionError("dht_factory called on a non-network process")
    )

slice_opt = SliceOptimizer(
    mesh=mesh, params=params, optimizer=opt, dht_factory=dht_factory,
    run_id="slice_full_opt", target_batch_size=TARGET, batch_size_per_step=16,
    load_state_timeout=30.0, **(common_av if proc_id == 0 else {}),
)
if proc_id != 0:
    # the structural claim: followers own NO networking objects at all
    assert slice_opt.dht is None and slice_opt.grad_averager is None
    assert slice_opt.state_averager is None and slice_opt.tracker is None

# deterministic gradients: slice contributes 1.0/2.0, host peer 3.0/4.0 — with
# equal sample weights the swarm average is w:2.0, b:3.0 per epoch, so after E
# epochs BOTH peers must hold exactly w0 - LR*2*E / b0 - LR*3*E (the large-batch
# equivalence the reference promises, optimizer.py:63-69)
g_slice = {
    "w": jax.device_put(np.full((8, 16), 1.0, np.float32), NamedSharding(mesh, P("dp"))),
    "b": jax.device_put(np.full(16, 2.0, np.float32), NamedSharding(mesh, P())),
}
g_host = {"w": jnp.full((8, 16), 3.0), "b": jnp.full(16, 4.0)}

EPOCHS = 2
stop = threading.Event()
def host_loop():
    # the host peer must stop at the SAME epoch as the slice: if it advanced solo,
    # the late joiner below would adopt a further-evolved state than expected
    while not stop.is_set() and host_opt.local_epoch < EPOCHS:
        host_opt.step(g_host, batch_size=16)
        time.sleep(0.25)

host_thread = None
if proc_id == 0:
    host_thread = threading.Thread(target=host_loop, daemon=True)
    host_thread.start()
deadline = time.monotonic() + 240
while slice_opt.local_epoch < EPOCHS and time.monotonic() < deadline:
    slice_opt.step(g_slice, batch_size=16)
    time.sleep(0.25)
assert slice_opt.local_epoch >= EPOCHS, f"[{proc_id}] stuck at epoch {slice_opt.local_epoch}"
epochs_done = slice_opt.local_epoch

expected_w = w0 - LR * 2.0 * epochs_done
expected_b = b0 - LR * 3.0 * epochs_done

def check_shards(arr, expected, atol):
    assert arr.addressable_shards, "process holds no shards"
    for shard in arr.addressable_shards:
        np.testing.assert_allclose(
            np.asarray(shard.data), expected[shard.index], rtol=0, atol=atol
        )

# every process verifies ITS shards: together both processes cover the arrays.
# fp16 grad+state compression => loose-ish tolerance
check_shards(slice_opt.params["w"], expected_w, 5e-3)
check_shards(slice_opt.params["b"], expected_b, 5e-3)
assert slice_opt.params["w"].sharding.spec == P("dp")
print(f"TRAIN_OK_{proc_id} epochs={epochs_done}", flush=True)

if proc_id == 0:
    hw = np.asarray(jax.device_get(host_opt.params["w"]))
    np.testing.assert_allclose(hw, expected_w, rtol=0, atol=5e-3)

# ---- late joiner: a FRESH slice (epoch 0) catches up through the tracker and
# adopts a donor's state COLLECTIVELY — the download must land on both
# processes' shards (VERDICT r3 done-bar)
if proc_id == 0:
    fresh_factory = lambda: DHT(initial_peers=maddrs, start=True)
else:
    fresh_factory = lambda: (_ for _ in ()).throw(AssertionError("follower built a DHT"))

fresh = SliceOptimizer(
    mesh=mesh,
    params={
        "w": jax.device_put(np.zeros((8, 16), np.float32), NamedSharding(mesh, P("dp"))),
        "b": jax.device_put(np.zeros(16, np.float32), NamedSharding(mesh, P())),
    },
    optimizer=opt, dht_factory=fresh_factory,
    run_id="slice_full_opt", target_batch_size=TARGET, batch_size_per_step=16,
    load_state_timeout=30.0, **(common_av if proc_id == 0 else {}),
)
deadline = time.monotonic() + 120
while fresh.local_epoch < epochs_done and time.monotonic() < deadline:
    fresh.step(None)  # no grads: pure catch-up through the tracker decision
    time.sleep(0.5)
assert fresh.local_epoch >= epochs_done, f"[{proc_id}] late joiner stuck at {fresh.local_epoch}"
check_shards(fresh.params["w"], expected_w, 5e-3)
check_shards(fresh.params["b"], expected_b, 5e-3)
print(f"JOIN_OK_{proc_id} epoch={fresh.local_epoch}", flush=True)

stop.set()
if host_thread is not None:
    host_thread.join(timeout=60)
fresh.shutdown()
slice_opt.shutdown()
if proc_id == 0:
    host_opt.shutdown(); host_dht.shutdown()
print(f"SLICE_OPT_OK_{proc_id}", flush=True)
"""


def test_full_optimizer_on_two_process_slice(tmp_path):
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = str(probe.getsockname()[1])
    script = tmp_path / "slice_opt_worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ, PYTHONPATH=os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if p]
    ))
    workers = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for i in range(2)
    ]
    try:
        for i, worker in enumerate(workers):
            out, _ = worker.communicate(timeout=540)
            assert worker.returncode == 0, f"worker {i} failed:\n{out[-4000:]}"
            assert f"TRAIN_OK_{i}" in out, out[-4000:]
            assert f"JOIN_OK_{i}" in out, out[-4000:]
            assert f"SLICE_OPT_OK_{i}" in out, out[-4000:]
    finally:
        for worker in workers:
            if worker.poll() is None:
                worker.kill()


def test_slice_collaborative_example_single_process():
    """The recipe in examples/slice_collaborative_training.py runs end to end on a
    single-process virtual mesh: a solo swarm still advances epochs (no round is
    attempted below 2 peers; local gradients apply) and the script exits cleanly."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the example sets its own device-count flag
    result = subprocess.run(
        [sys.executable, os.path.join(repo, "examples", "slice_collaborative_training.py"),
         "--platform", "cpu", "--devices_per_proc", "4", "--steps", "24",
         "--target_batch_size", "64", "--batch_size", "32", "--dim", "16"],
        capture_output=True, text=True, timeout=420, env=env,
    )
    assert result.returncode == 0, (result.stdout + result.stderr)[-3000:]
    combined = result.stdout + result.stderr
    assert "done: epoch" in combined, combined[-2000:]
    final_epoch = int(combined.rsplit("done: epoch", 1)[1].strip().split()[0])
    assert final_epoch >= 5, combined[-2000:]


def test_slice_optimizer_state_dict_roundtrip():
    """Checkpoint parity with the host Optimizer (reference optimizer.py:719-727):
    state_dict embeds the epoch and every averaged tensor (params + adam mu/nu);
    load_state_dict restores them onto the sharded device state and fast-forwards
    the optax counters, so one identical post-restore epoch update matches the
    original run exactly."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import SliceOptimizer

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    boot = DHT(start=True)
    opt = SliceOptimizer(
        mesh=mesh, params={"w": jax.device_put(np.ones((8, 4), np.float32), sharding)},
        optimizer=optax.adam(0.1), dht_factory=lambda: boot,
        run_id="ckpt_rt", target_batch_size=8, batch_size_per_step=8,
    )
    fresh = None
    try:
        g = {"w": jnp.full((8, 4), 1.0)}
        deadline = time.monotonic() + 90
        while opt.local_epoch < 3 and time.monotonic() < deadline:
            opt.step(g, batch_size=8)
            time.sleep(0.2)
        assert opt.local_epoch >= 3
        checkpoint = opt.state_dict()
        assert checkpoint["epoch"] == opt.local_epoch
        assert len(checkpoint["tensors"]) == 3  # params + adam mu + nu
        trained = np.asarray(jax.device_get(opt.params["w"]))

        # a DIFFERENT run_id: the restore target must not share a swarm with the
        # original — otherwise the original's tracker records can flip the
        # restored peer into the catch-up path mid-comparison (it would download
        # state instead of applying its gradient, making the adam assertion
        # vacuous) and 2-peer trackers would try real averaging rounds
        fresh = SliceOptimizer(
            mesh=mesh, params={"w": jax.device_put(np.zeros((8, 4), np.float32), sharding)},
            optimizer=optax.adam(0.1),
            dht_factory=lambda: DHT(
                initial_peers=[str(m) for m in boot.get_visible_maddrs()], start=True
            ),
            run_id="ckpt_rt_restored", target_batch_size=8, batch_size_per_step=8,
        )
        fresh.load_state_dict(checkpoint)
        assert fresh.local_epoch == checkpoint["epoch"]
        np.testing.assert_allclose(
            np.asarray(jax.device_get(fresh.params["w"])), trained, atol=1e-6
        )
        # adam statistics restored: one identical (solo, local-gradient) epoch
        # update on both sides must produce identical params. Exactly ONE
        # transition each: if step() already fired it via the tracker, forcing a
        # second would apply a spurious zero-grad adam update
        for instance in (opt, fresh):
            before = instance.local_epoch
            instance.step(g, batch_size=8)
            if instance.local_epoch == before:
                instance.force_epoch_transition()
        np.testing.assert_allclose(
            np.asarray(jax.device_get(fresh.params["w"])),
            np.asarray(jax.device_get(opt.params["w"])), atol=1e-6,
        )
    finally:
        if fresh is not None:
            fresh.shutdown()
        opt.shutdown()


def test_slice_optimizer_with_powersgd_interoperates_with_host_peer():
    """PowerSGD gradient compression on the slice tier: a SliceOptimizer with a
    PowerSGDGradientAverager factory trains in lockstep with a host Optimizer
    peer using the same factory. Constant gradients are exactly rank-1, so the
    factorized rounds are lossless and both peers must land on the exact
    large-batch average — and on each other."""
    import threading
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import Optimizer, PowerSGDGradientAverager, SliceOptimizer

    import functools

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    LR, TARGET = 0.1, 32
    # a partial (not a lambda) lets SliceOptimizer see the class and skip the
    # host accumulator allocation (its accumulation lives on device)
    factory = functools.partial(PowerSGDGradientAverager, averager_rank=1)

    boot = DHT(start=True)
    slice_opt = SliceOptimizer(
        mesh=mesh, params={"w": jax.device_put(np.zeros((8, 16), np.float32), sharding)},
        optimizer=optax.sgd(LR), dht_factory=lambda: boot,
        run_id="psgd_slice", target_batch_size=TARGET, batch_size_per_step=8,
        target_group_size=2, matchmaking_time=1.5, averaging_timeout=40.0,
        grad_averager_factory=factory,
    )
    q_seed = np.array(slice_opt.grad_averager._qs[0])  # warm-start Q before any round
    host_dht = DHT(initial_peers=[str(m) for m in boot.get_visible_maddrs()], start=True)
    host_opt = Optimizer(
        dht=host_dht, run_id="psgd_slice", params={"w": jnp.zeros((8, 16))},
        optimizer=optax.sgd(LR), target_batch_size=TARGET, batch_size_per_step=8,
        target_group_size=2, matchmaking_time=1.5, averaging_timeout=40.0,
        grad_averager_factory=factory,
    )
    g_slice = {"w": jax.device_put(np.full((8, 16), 2.0, np.float32), sharding)}
    g_host = {"w": jnp.full((8, 16), 4.0)}
    EPOCHS = 2
    stop = threading.Event()

    def host_loop():
        while not stop.is_set() and host_opt.local_epoch < EPOCHS:
            host_opt.step(g_host, batch_size=8)
            time.sleep(0.2)

    thread = threading.Thread(target=host_loop, daemon=True)
    thread.start()
    try:
        deadline = time.monotonic() + 180
        while slice_opt.local_epoch < EPOCHS and time.monotonic() < deadline:
            slice_opt.step(g_slice, batch_size=8)
            time.sleep(0.2)
        assert slice_opt.local_epoch >= EPOCHS, f"stuck at {slice_opt.local_epoch}"
        epochs = slice_opt.local_epoch
        # the slice loop exits the moment IT transitions; let the host finish its
        # own epoch-2 transition before comparing (its thread stops itself there)
        settle = time.monotonic() + 60
        while host_opt.local_epoch < epochs and time.monotonic() < settle:
            time.sleep(0.2)
        stop.set()
        thread.join(timeout=60)
        assert host_opt.local_epoch >= epochs, f"host stuck at {host_opt.local_epoch}"
        # the device-side accumulation path really skipped the host buffers
        assert slice_opt.grad_averager._grad_accumulators is None
        sw = np.asarray(jax.device_get(slice_opt.params["w"]))
        hw = np.asarray(jax.device_get(host_opt.params["w"]))
        # both peers ADOPT the same factorized group average every epoch, so they
        # must agree exactly — regardless of how the sample split landed; the
        # value itself sits between the all-slice and all-host extremes (the
        # weighted mean of grads 2.0 and 4.0)
        np.testing.assert_allclose(sw, hw, atol=5e-3)
        assert (-LR * 4.0 * epochs - 5e-3) <= sw[0, 0] <= (-LR * 2.0 * epochs + 5e-3), sw[0, 0]
        # the compressed rounds really happened: a successful P/Q round replaces
        # the warm-start Q (seeded 0xC0FFEE) with the orthogonalized average
        assert not np.allclose(slice_opt.grad_averager._qs[0], q_seed), (
            "warm-start Q unchanged: no factorized round ever completed"
        )
    finally:
        stop.set()
        thread.join(timeout=60)
        slice_opt.shutdown()
        host_opt.shutdown()
        host_dht.shutdown()


def test_network_process_failure_raises_in_lockstep_not_hangs():
    """Advisor r4 medium finding: if process 0's networking raises inside step()'s
    decision phase (DHT store failure, tracker shutdown), it must STILL broadcast
    — with the error flag set — so followers raise in lockstep instead of parking
    forever in the collective. On one process we can assert the p0 half: the
    original exception propagates (after the sentinel broadcast) rather than
    being swallowed or skipping the broadcast."""
    import jax
    import numpy as np
    import optax
    import pytest
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import SliceOptimizer

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    opt = SliceOptimizer(
        mesh=mesh, params={"w": jax.device_put(np.ones((8, 4), np.float32), sharding)},
        optimizer=optax.sgd(0.1), dht_factory=lambda: DHT(start=True),
        run_id="sentinel_bcast", target_batch_size=64, batch_size_per_step=4,
    )
    try:
        g = {"w": jax.device_put(np.ones((8, 4), np.float32), sharding)}
        opt.step(g, batch_size=4)  # sanity: a healthy step works

        def boom(*args, **kwargs):
            raise OSError("injected: dht store failed")

        opt.tracker.report_local_progress = boom
        with pytest.raises(OSError, match="injected: dht store failed"):
            opt.step(g, batch_size=4)
    finally:
        opt.shutdown()


def test_slice_chronic_failure_counter_and_backoff():
    """Host-Optimizer parity (optimizer.py:100-136): consecutive failed swarm
    rounds escalate to chronic failure, matchmaking lead time backs off
    exponentially (capped 8x), pre-scheduling is suppressed while chronic, and
    one success resets everything. Pure unit math — no network."""
    from hivemind_tpu.optim import SliceOptimizer

    opt = SliceOptimizer.__new__(SliceOptimizer)
    opt.matchmaking_time = 2.0
    opt.chronic_failure_threshold = 3
    opt._consecutive_failed_rounds = 0
    opt.is_network_process = True

    assert not opt.chronic_averaging_failure
    assert opt._matchmaking_delay() == 2.0
    opt._record_round_outcome(None)  # solo swarm: neither failure nor recovery
    assert opt.consecutive_failed_averaging_rounds == 0

    for _ in range(3):
        opt._record_round_outcome(False)
    assert opt.chronic_averaging_failure
    assert opt._matchmaking_delay() == 4.0  # 2.0 * 2^1
    opt._record_round_outcome(False)
    assert opt._matchmaking_delay() == 8.0
    for _ in range(10):
        opt._record_round_outcome(False)
    assert opt._matchmaking_delay() == 16.0  # capped at 8x

    opt._record_round_outcome(True)  # recovery resets
    assert opt.consecutive_failed_averaging_rounds == 0
    assert not opt.chronic_averaging_failure
    assert opt._matchmaking_delay() == 2.0
