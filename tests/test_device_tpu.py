"""On-device (TPU) kernel validation — the manual-run twin of the checks
`bench.py` embeds in the round artifact whenever the chip answers.

On CPU runners this exercises the same code in interpret mode (cheap smoke);
on a real TPU it validates Mosaic-compiled kernels. Run on hardware with:
``python -m pytest tests/test_device_tpu.py -q`` after unsetting the CPU pin.
"""

import jax


def test_validate_on_device_report():
    from hivemind_tpu.ops.device_check import validate_on_device

    report = validate_on_device(seq=256)
    assert report["backend"] == jax.default_backend()
    expected = {
        "flash_fwd_bidir", "flash_fwd_causal", "flash_bwd_bidir", "flash_bwd_causal",
        "blockwise_int8_roundtrip",
    }
    assert expected <= set(report["checks"]) | set(report["errors"]), report
    assert report["ok"], report
    assert report["attention_ok"], report
    for name, err in report["checks"].items():
        if name.startswith("flash"):
            assert err < 2e-2, (name, err)
