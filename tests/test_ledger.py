"""Round ledger (ISSUE 8): record assembly from a real two-peer averaging
round, straggler scoring, the DHT snapshot size budget, the ``GET /ledger``
round-trip, epoch rollups, and the ``hivemind-top`` / epoch-timeline renders."""

import json
import time
import urllib.request

import numpy as np

from hivemind_tpu.averaging import DecentralizedAverager
from hivemind_tpu.telemetry import LEDGER, MetricsExporter
from hivemind_tpu.telemetry.ledger import RoundLedger
from hivemind_tpu.telemetry.tracing import finish_span, start_span, trace

from swarm_utils import launch_dht_swarm, shutdown_all


def _synthetic_round(ledger: RoundLedger, exchanges, local_reduce_s=0.001, matchmaking=True):
    """Feed one round's spans straight into a ledger: exchanges is a list of
    (remote, seconds)."""
    if matchmaking:
        with trace("averaging.matchmaking", peer="me") as span:
            span.set("outcome", "assembled")
    round_span = start_span("allreduce.round", peer="me", group_size=len(exchanges) + 1, rank=0)
    local = start_span("allreduce.local_reduce", parent=round_span, peer="me")
    local.start -= local_reduce_s  # backdate instead of sleeping
    finish_span(local)
    for remote, seconds in exchanges:
        exchange = start_span("allreduce.peer_exchange", parent=round_span, peer="me", remote=remote)
        exchange.start -= seconds
        finish_span(exchange)
        ledger.on_span(exchange)
    ledger.on_span(local)
    if matchmaking:
        ledger.on_span(span)
    # the round wall time covers its phases: backdate like the children
    round_span.start -= max((seconds for _remote, seconds in exchanges), default=0.0) + local_reduce_s
    finish_span(round_span)
    ledger.on_span(round_span)


# ------------------------------------------------------------------ assembly


def test_record_assembly_from_real_two_peer_round():
    """The global LEDGER assembles records from the spans a REAL two-peer
    all-reduce produces — phases, partner attribution, matchmaking wait."""
    LEDGER.clear()
    dhts = launch_dht_swarm(2)
    averagers = []
    for i, dht in enumerate(dhts):
        tensors = [np.full(64, float(i), np.float32)]
        averagers.append(
            DecentralizedAverager(
                tensors, dht, prefix="ledgertest", start=True, target_group_size=2,
                min_matchmaking_time=1.0, request_timeout=1.0,
            )
        )
    try:
        controls = [a.step(wait=False, timeout=30) for a in averagers]
        for control in controls:
            control.result(timeout=60)
        with averagers[0].get_tensors() as tensors:
            assert np.allclose(tensors[0], 0.5)
        records = LEDGER.records()
        # both peers live in this process: one record per peer's round (an
        # exchange span may still be mid-cancellation when its round closes, so
        # attribution is asserted on the records that carry it)
        assert len(records) >= 2, records
        peer_ids = {str(a.peer_id) for a in averagers}
        assert {record["peer"] for record in records} == peer_ids
        for record in records:
            assert record["group_size"] == 2
            assert record["total_s"] > 0
        attributed = [record for record in records if "slowest_peer" in record]
        assert attributed, records
        for record in attributed:
            # the one exchange partner is the OTHER peer
            assert record["slowest_peer"] in peer_ids - {record["peer"]}
            assert record["slowest_s"] > 0
        assert any("local_reduce_s" in record for record in records), records
        assert any("matchmaking_wait_s" in record for record in records), records
        scores = LEDGER.straggler_scores()
        assert set(scores) <= peer_ids and scores
        assert all(score["rounds_slowest"] >= 1 for score in scores.values())
    finally:
        shutdown_all(averagers, dhts)


def test_straggler_scoring_names_the_slow_partner():
    ledger = RoundLedger()
    for _ in range(3):
        _synthetic_round(ledger, [("slowpoke", 0.5), ("fast1", 0.01), ("fast2", 0.012)])
    _synthetic_round(ledger, [("fast1", 0.02), ("fast2", 0.01)])
    scores = ledger.straggler_scores()
    worst = next(iter(scores))
    assert worst == "slowpoke"
    assert scores["slowpoke"]["rounds_slowest"] == 3
    # excess is measured over the round's median exchange, so ~0.49/round
    assert scores["slowpoke"]["excess_s"] > 1.0
    assert scores["fast1"]["rounds_slowest"] == 1  # slowest of the last round
    records = ledger.records()
    assert len(records) == 4
    assert records[0]["slowest_peer"] == "slowpoke"
    assert records[0]["exchange_spread_s"] > 0.4
    summary = ledger.summary()
    assert summary["rounds"] == 4
    assert summary["total_s"]["p95"] >= summary["total_s"]["mean"]
    assert "slowpoke" in summary["stragglers"]


def test_epoch_rollup_carries_rounds_and_straggler():
    ledger = RoundLedger()
    _synthetic_round(ledger, [("laggard", 0.2), ("quick", 0.01)])
    _synthetic_round(ledger, [("laggard", 0.3), ("quick", 0.02)])
    entry = ledger.record_epoch(7, peer="me", averaged_ok=True, num_peers=3)
    assert entry["epoch"] == 7 and entry["rounds"] == 2
    assert entry["straggler"] == "laggard"
    assert entry["round_s"] > 0.5
    # the rollup window resets: the next epoch only sees its own rounds
    entry2 = ledger.record_epoch(8, peer="me", averaged_ok=False, num_peers=3)
    assert entry2["rounds"] == 0 and "straggler" not in entry2
    assert [e["epoch"] for e in ledger.epochs()] == [7, 8]


def test_epoch_windows_are_per_peer():
    """Several optimizers share one process (and this singleton) in soaks:
    peer A's transition must consume only A's rounds, not B's."""
    ledger = RoundLedger()

    def _round_for(peer, remote, seconds):
        round_span = start_span("allreduce.round", peer=peer, group_size=2, rank=0)
        exchange = start_span("allreduce.peer_exchange", parent=round_span, peer=peer, remote=remote)
        exchange.start -= seconds
        finish_span(exchange)
        ledger.on_span(exchange)
        round_span.start -= seconds
        finish_span(round_span)
        ledger.on_span(round_span)

    _round_for("peerA", "slowX", 0.2)
    _round_for("peerB", "slowY", 0.3)
    _round_for("peerA", "slowX", 0.1)
    entry_a = ledger.record_epoch(4, peer="peerA")
    assert entry_a["rounds"] == 2 and entry_a["straggler"] == "slowX"
    entry_b = ledger.record_epoch(4, peer="peerB")
    assert entry_b["rounds"] == 1 and entry_b["straggler"] == "slowY"
    assert abs(entry_b["round_s"] - 0.3) < 0.05


def test_late_exchange_retroattaches_and_reattributes():
    """The slowest partner's exchange span usually finishes AFTER its round's
    record closed (its delta completes the round output while the stream close
    is still in flight): the ledger must fold it in and move the round's
    straggler credit — otherwise it would drop exactly the peer it exists to
    name."""
    ledger = RoundLedger()
    round_span = start_span("allreduce.round", peer="me", group_size=3, rank=0)
    fast = start_span("allreduce.peer_exchange", parent=round_span, peer="me", remote="fast")
    fast.start -= 0.01
    finish_span(fast)
    ledger.on_span(fast)
    finish_span(round_span)
    ledger.on_span(round_span)
    assert ledger.records()[0]["slowest_peer"] == "fast"  # best knowledge so far
    # the true straggler's span lands after the round already closed
    late = start_span("allreduce.peer_exchange", parent=round_span, peer="me", remote="laggard")
    late.start -= 0.4
    late.add_event("retry")
    finish_span(late)
    ledger.on_span(late)
    record = ledger.records()[0]
    assert record["slowest_peer"] == "laggard" and record["slowest_s"] > 0.3
    assert len(record["exchanges"]) == 2
    assert record["events"]["retry"] == 1
    scores = ledger.straggler_scores()
    assert scores["laggard"]["rounds_slowest"] == 1
    assert scores["fast"]["rounds_slowest"] == 0  # its interim credit was retracted
    assert scores["fast"]["total_s"] > 0  # but its exchange time still counts


def test_concurrent_rounds_do_not_cross_contaminate():
    """Two interleaved rounds (grad + state averager share one process): each
    record only contains its own round's exchanges, keyed by parent span."""
    ledger = RoundLedger()
    round_a = start_span("allreduce.round", peer="me", group_size=2, rank=0)
    round_b = start_span("allreduce.round", peer="me", group_size=2, rank=1)
    for parent, remote, seconds in ((round_a, "peerA", 0.1), (round_b, "peerB", 0.2)):
        exchange = start_span("allreduce.peer_exchange", parent=parent, peer="me", remote=remote)
        exchange.start -= seconds
        finish_span(exchange)
        ledger.on_span(exchange)
    for round_span in (round_b, round_a):
        finish_span(round_span)
        ledger.on_span(round_span)
    records = {r["rank"]: r for r in ledger.records()}
    assert records[0]["slowest_peer"] == "peerA"
    assert records[1]["slowest_peer"] == "peerB"


# ------------------------------------------------------------------ budget


def test_snapshot_respects_dht_size_budget():
    from hivemind_tpu.telemetry.monitor import _shrink_to_fit
    from hivemind_tpu.utils.serializer import MSGPackSerializer

    ledger = RoundLedger()
    for index in range(200):
        _synthetic_round(ledger, [(f"peer-{index % 17}-{'x' * 40}", 0.01 + index * 1e-4)])
    compact = ledger.snapshot()
    # the compact view is bounded regardless of history length
    assert len(compact["records"]) <= 8
    assert len(compact["stragglers"]) <= 5
    assert all("exchanges" not in record for record in compact["records"])

    snapshot = {"time": 0.0, "metrics": {}, "ledger": compact}
    for budget in (4096, 1024, 256):
        shrunk = _shrink_to_fit(dict(snapshot), max_bytes=budget)
        assert len(MSGPackSerializer.dumps(shrunk)) <= budget
    # at a tight budget the bulky records go before the straggler scores do,
    # and at the tightest the whole ledger section is dropped, never a crash
    shrunk = _shrink_to_fit(dict(snapshot), max_bytes=1024)
    ledger_part = shrunk.get("ledger")
    assert ledger_part is None or "records" not in ledger_part or shrunk.get("truncated")


# ------------------------------------------------------------------ endpoint


def test_ledger_endpoint_roundtrip():
    ledger = RoundLedger()
    _synthetic_round(ledger, [("slowpoke", 0.25), ("quick", 0.01)])
    ledger.record_epoch(3, peer="me", averaged_ok=True, num_peers=2)
    exporter = MetricsExporter(port=0, ledger=ledger)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/ledger", timeout=5
        ).read()
    finally:
        exporter.shutdown()
    doc = json.loads(body)
    assert doc["records"][0]["slowest_peer"] == "slowpoke"
    assert doc["records"][0]["exchanges"][0]["remote"] == "slowpoke"  # raw, not compacted
    assert doc["straggler_scores"]["slowpoke"]["rounds_slowest"] == 1
    assert doc["epochs"][0]["epoch"] == 3 and doc["epochs"][0]["straggler"] == "slowpoke"
    assert doc["summary"]["rounds"] == 1


# ------------------------------------------------------------------ rendering


def _monitor_fixture_records():
    """Two-peer snapshot fixture: one healthy, one stale straggler-victimized
    peer with a stalled loop — every dashboard column has something to show."""
    now = time.time()
    healthy = {
        "peer_id": "peerHealthy",
        "time": now - 2.0,
        "metrics": {
            "hivemind_optim_local_epoch": {"type": "gauge", "series": {"_": 12}},
            "hivemind_optim_local_samples_accumulated": {"type": "gauge", "series": {"_": 640}},
            "hivemind_event_loop_lag_seconds": {
                "type": "histogram", "series": {"loop=hmtpu-loop": {"count": 100, "sum": 0.05}},
            },
        },
        "ledger": {
            "stragglers": {"peerStale": {"rounds_slowest": 4, "excess_s": 1.25, "total_s": 3.0}},
            "records": [{"round": 1, "slowest_peer": "peerStale", "total_s": 0.5, "group_size": 2}],
            "epochs": [
                {"epoch": 11, "peer": "peerHealthy", "rounds": 2, "round_s": 0.9,
                 "straggler": "peerStale", "averaged_ok": True},
                {"epoch": 12, "peer": "peerHealthy", "rounds": 1, "round_s": 0.4, "averaged_ok": True},
            ],
        },
    }
    stale = {
        "peer_id": "peerStale",
        "time": now - 500.0,  # way past 3x any sane publish interval
        "metrics": {
            "hivemind_optim_local_epoch": {"type": "gauge", "series": {"_": 9}},
            "hivemind_event_loop_stalls_total": {"type": "counter", "series": {"loop=hmtpu-loop": 2}},
        },
        "watchdog": {
            "loops": ["hmtpu-loop"], "stalls": 2, "max_lag_s": 1.7,
            "last_stall": {"time": now - 510.0, "loop": "hmtpu-loop", "blocked_s_at_capture": 1.5},
        },
        "breakers": {"dht_blacklist": {"num_tripped": 1, "tripped": ["peerGone"]}},
        "slow_spans": [{"name": "allreduce.round", "dur_ms": 9000.0, "events": ["error"]}],
    }
    return {"peerHealthy": healthy, "peerStale": stale}


def test_run_top_render_smoke():
    from hivemind_tpu.hivemind_cli.run_top import render_frame

    records = _monitor_fixture_records()
    frame, samples = render_frame(records, publish_interval=30.0, ansi=False)
    assert "hivemind-top" in frame and "2 peer(s)" in frame
    assert "peerHealthy" in frame and "peerStale" in frame
    assert "STALE" in frame and "LOOP-STALLED" in frame and "BREAKERS" in frame
    assert "stragglers" in frame and "slowest in    4 round(s)" in frame
    assert "recent alerts" in frame and "allreduce.round" in frame
    assert "peerHealthy" in samples  # samples gauge captured for the rate column
    # second frame computes the samples/s column from the delta
    records["peerHealthy"]["metrics"]["hivemind_optim_local_samples_accumulated"]["series"]["_"] = 740
    frame2, _ = render_frame(
        records, publish_interval=30.0, ansi=False,
        prev_samples={k: (v[0], v[1] - 10.0) for k, v in samples.items()},
    )
    assert "10.0" in frame2  # 100 samples over 10 s
    # ANSI mode prefixes the clear-screen control sequence
    ansi_frame, _ = render_frame(records, publish_interval=30.0, ansi=True)
    assert ansi_frame.startswith("\x1b[2J\x1b[H")


def test_renders_survive_malformed_peer_snapshot():
    """Snapshots come from the DHT: one buggy/hostile peer must get a flagged
    row, not kill every operator's dashboard or report."""
    from hivemind_tpu.hivemind_cli.run_top import render_frame
    from hivemind_tpu.telemetry.monitor import SwarmMonitor, aggregate_swarm_view

    records = _monitor_fixture_records()
    records["peerEvil"] = {
        "time": "not-a-number",
        "metrics": "nope",
        "ledger": {"epochs": [{"epoch": None}, {"epoch": 3, "rounds": "many", "round_s": {}}],
                   "stragglers": {"x": {"rounds_slowest": "NaNish"}}},
        "watchdog": [],
    }
    frame, _ = render_frame(records, publish_interval=30.0, ansi=False)
    assert "<malformed snapshot>" in frame
    assert "peerHealthy" in frame  # healthy peers still render fully

    monitor = SwarmMonitor.__new__(SwarmMonitor)
    report = monitor.render_report(aggregate_swarm_view(
        {k: v for k, v in records.items() if isinstance(v.get("time"), (int, float)) or k == "peerEvil"}
    ))
    assert "epoch timeline" in report  # healthy entries survive
    assert "<malformed ledger entry>" in report or "epoch 3" in report


def test_render_report_epoch_timeline_and_stale_flag():
    from hivemind_tpu.telemetry.monitor import SwarmMonitor, aggregate_swarm_view

    monitor = SwarmMonitor.__new__(SwarmMonitor)
    monitor.publish_interval = 30.0
    view = aggregate_swarm_view(_monitor_fixture_records())
    report = monitor.render_report(view)
    assert "STALE" in report, report
    assert "epoch timeline" in report and "epoch 11" in report
    assert "slowest=peerStale" in report
    assert "WATCHDOG: 2 event-loop stall(s)" in report
    assert "straggler seen: peerStale" in report
    # the raw ledger/watchdog dicts must not be dumped inline on the peer line
    peer_line = next(line for line in report.splitlines() if "peerHealthy" in line and "peer " in line)
    assert "stragglers" not in peer_line
