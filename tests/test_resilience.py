"""ISSUE 3 resilience layer: RetryPolicy/Deadline semantics, circuit-breaker
state machine + telemetry, chaos-engine determinism and spec grammar, DHT
churn under injected rpc drops, the ad-hoc-retry lint, and a chaos-soak smoke.

Everything here is seeded and CPU-only; the multi-minute soak lives behind the
``slow`` marker (the ``chaos`` marker alone stays tier-1-safe)."""

import asyncio
import time

import numpy as np
import pytest

from hivemind_tpu.dht.node import Blacklist, DHTNode
from hivemind_tpu.resilience import (
    CHAOS,
    BreakerBoard,
    BreakerState,
    ChaosAbort,
    ChaosDrop,
    ChaosEngine,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    reset_all_boards,
)
from hivemind_tpu.telemetry import REGISTRY
from hivemind_tpu.utils.timed_storage import get_dht_time


# ---------------------------------------------------------------------- policy


async def test_deadline_budget_propagates():
    budget = Deadline(0.2)
    assert not budget.expired and 0.0 < budget.remaining() <= 0.2
    assert budget.remaining_or(0.05) <= 0.05  # per-step cap wins while budget is fat
    # a nested wait consumes the SHARED budget, not an independent timeout
    with pytest.raises(DeadlineExceeded):
        await budget.wait_for(asyncio.sleep(5.0))
    assert budget.expired and budget.remaining() == 0.0
    with pytest.raises(DeadlineExceeded):
        await budget.wait_for(asyncio.sleep(0.0))  # already spent: fails instantly
    assert Deadline(None).remaining() is None  # unlimited budget
    assert await Deadline(None).wait_for(_value(7)) == 7


async def _value(x):
    return x


async def test_retry_policy_async_retries_then_succeeds():
    calls = []

    async def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("transient")
        return "ok"

    policy = RetryPolicy(max_attempts=5, base_delay=0.001, name="test_site")
    assert await policy.execute(lambda: flaky()) == "ok"
    assert len(calls) == 3


async def test_retry_policy_respects_attempt_cap_and_predicate():
    policy = RetryPolicy(max_attempts=3, base_delay=0.001)

    attempts = []

    async def always_fails():
        attempts.append(1)
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        await policy.execute(lambda: always_fails())
    assert len(attempts) == 3

    # non-retryable types pass straight through
    picky = RetryPolicy(max_attempts=5, base_delay=0.001, retry_on=(ConnectionError,))

    async def type_error():
        attempts.append(1)
        raise TypeError("bug, not weather")

    attempts.clear()
    with pytest.raises(TypeError):
        await picky.execute(lambda: type_error())
    assert len(attempts) == 1

    # a spent deadline stops retries even under the attempt cap
    async def fails():
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        await RetryPolicy(max_attempts=100, base_delay=0.001).execute(
            lambda: fails(), deadline=Deadline(0.0)
        )


def test_retry_policy_sync_and_jitter_bounds():
    import random

    rng = random.Random(0)
    policy = RetryPolicy(base_delay=1.0, backoff=2.0, max_delay=3.0, jitter="none")
    assert [policy.delay(i) for i in range(4)] == [1.0, 2.0, 3.0, 3.0]
    equal = RetryPolicy(base_delay=1.6, backoff=1.0, jitter="equal")
    for _ in range(50):
        assert 0.8 <= equal.delay(0, rng) <= 1.6
    full = RetryPolicy(base_delay=1.0, jitter="full")
    for _ in range(50):
        assert 0.0 <= full.delay(0, rng) <= 1.0

    calls = []

    def flaky_sync():
        calls.append(1)
        if len(calls) < 2:
            raise OSError("transient")
        return 42

    sleeps = []
    result = RetryPolicy(max_attempts=3, base_delay=0.5, jitter="none").execute_sync(
        flaky_sync, sleep=sleeps.append
    )
    assert result == 42 and sleeps == [0.5]


# ---------------------------------------------------------------------- breaker


def test_breaker_trip_threshold_and_recovery():
    board = BreakerBoard("t_trip", failure_threshold=3, recovery_time=0.1, backoff_rate=2.0)
    board.register_failure("peer")
    board.register_failure("peer")
    assert "peer" not in board and board.state("peer") is BreakerState.CLOSED
    board.register_failure("peer")  # third consecutive failure trips it
    assert "peer" in board and board.state("peer") is BreakerState.OPEN
    assert board.trip_count("peer") == 1
    # a success anywhere before threshold resets the consecutive count
    board.register_success("peer")
    assert board.state("peer") is BreakerState.CLOSED and board.all_closed()
    board.register_failure("other")
    board.register_success("other")
    board.register_failure("other")
    board.register_failure("other")
    assert "other" not in board  # 2 failures after the reset: under threshold


def test_breaker_half_open_probe_success_and_failure():
    board = BreakerBoard("t_probe", failure_threshold=1, recovery_time=0.05, backoff_rate=2.0)
    board.register_failure("peer")
    assert board.state("peer") is BreakerState.OPEN and not board.allow("peer")
    time.sleep(0.06)
    assert board.state("peer") is BreakerState.HALF_OPEN
    assert "peer" not in board  # pure read: half-open is not banned
    assert board.allow("peer") and not board.allow("peer")  # one probe slot
    # probe FAILURE re-opens with a doubled window
    board.register_failure("peer")
    assert board.state("peer") is BreakerState.OPEN and board.trip_count("peer") == 2
    time.sleep(0.06)
    assert board.state("peer") is BreakerState.OPEN  # 0.1 s window now
    time.sleep(0.06)
    assert board.state("peer") is BreakerState.HALF_OPEN
    # probe SUCCESS closes and fully resets
    assert board.allow("peer")
    board.register_success("peer")
    assert board.state("peer") is BreakerState.CLOSED and board.all_closed()


def test_breaker_telemetry_emission():
    trips = REGISTRY.get("hivemind_breaker_trips_total")
    probes = REGISTRY.get("hivemind_breaker_probe_outcomes_total")
    tripped = REGISTRY.get("hivemind_breaker_tripped")
    board = BreakerBoard("t_telemetry", failure_threshold=1, recovery_time=0.05)
    trips_before = trips.value(board="t_telemetry")
    board.register_failure("a")
    board.register_failure("b")
    assert trips.value(board="t_telemetry") == trips_before + 2
    assert tripped.value(board="t_telemetry") == 2
    time.sleep(0.06)
    board.register_success("a")  # half-open probe success
    board.register_failure("b")  # half-open probe failure
    assert probes.value(board="t_telemetry", outcome="success") >= 1
    assert probes.value(board="t_telemetry", outcome="failure") >= 1
    assert tripped.value(board="t_telemetry") == 1
    board.clear()
    assert tripped.value(board="t_telemetry") == 0


def test_dht_blacklist_is_a_breaker_board():
    """The DHT Blacklist API (register_failure/success, `in`, ban_counter, clear)
    rides the shared breaker with its historical backoff semantics."""
    blacklist = Blacklist(base_time=0.1, backoff_rate=2.0)
    peer = "peer_id_stub"
    blacklist.register_failure(peer)
    assert peer in blacklist and blacklist.ban_counter.get(peer, 0) == 1
    # failures while banned do not escalate (historical semantics)
    blacklist.register_failure(peer)
    assert blacklist.ban_counter.get(peer) == 1
    blacklist.register_success(peer)
    assert peer not in blacklist and blacklist.ban_counter.get(peer, 0) == 0
    # base_time=0 disables banning entirely
    disabled = Blacklist(base_time=0.0)
    disabled.register_failure(peer)
    assert peer not in disabled
    blacklist.clear()


# ---------------------------------------------------------------------- chaos


async def test_chaos_spec_grammar_and_determinism():
    engine = ChaosEngine()
    engine.configure("seed=11;dht.rpc_find:drop:prob=0.4;p2p.unary.send:delay:delay=0.001:after=2")
    assert len(engine.rules) == 2 and engine.enabled

    async def decisions(e):
        out = []
        for _ in range(30):
            try:
                await e.inject("dht.rpc_find")
                out.append(0)
            except ChaosDrop:
                out.append(1)
        return out

    first = await decisions(engine)
    engine.configure("seed=11;dht.rpc_find:drop:prob=0.4;p2p.unary.send:delay:delay=0.001:after=2")
    second = await decisions(engine)
    assert first == second and 0 < sum(first) < 30  # seeded: identical, non-trivial
    engine.configure("seed=12;dht.rpc_find:drop:prob=0.4")
    third = await decisions(engine)
    assert third != first  # different seed, different schedule


async def test_chaos_after_times_scope_and_corrupt():
    engine = ChaosEngine()
    engine.reseed(3)
    engine.add_rule("allreduce.load", "abort", after=2, times=1, scope="victim")
    # wrong scope: never fires
    for _ in range(5):
        await engine.inject("allreduce.load", scope="healthy_peer")
    # right scope: skips 2, fires once, then is exhausted
    await engine.inject("allreduce.load", scope="the_victim_peer")
    await engine.inject("allreduce.load", scope="the_victim_peer")
    with pytest.raises(ChaosAbort):
        await engine.inject("allreduce.load", scope="the_victim_peer")
    await engine.inject("allreduce.load", scope="the_victim_peer")  # times=1 spent
    assert engine.stats() == {"allreduce.load:abort": 1}

    engine.clear()
    engine.add_rule("p2p.unary.send", "corrupt_payload")
    original = b"\x00" * 512
    corrupted = await engine.inject("p2p.unary.send", payload=original)
    assert corrupted != original and len(corrupted) == len(original)
    # non-byte payloads pass through corruption untouched
    assert await engine.inject("p2p.unary.send", payload={"not": "bytes"}) == {"not": "bytes"}


async def test_chaos_link_scope_directional_matching():
    """ISSUE 12: ``scope=link:<src>-><dst>`` rules fault exactly one direction
    of one link (wildcard ends supported); non-link call sites never match a
    link rule, and plain peer-substring rules still match link scopes because
    the link string carries both endpoint ids."""
    engine = ChaosEngine()
    engine.reseed(5)
    engine.add_rule("p2p.unary.send", "abort", scope="link:alice->bob")
    # matching direction fires
    with pytest.raises(ChaosAbort):
        await engine.inject("p2p.unary.send", scope="link:alice->bob")
    # reverse direction and other links do not
    await engine.inject("p2p.unary.send", scope="link:bob->alice")
    await engine.inject("p2p.unary.send", scope="link:alice->carol")
    # a non-link call site (plain peer scope) never matches a link rule
    await engine.inject("p2p.unary.send", scope="alice")
    assert engine.stats() == {"p2p.unary.send:abort": 1}

    engine.clear()
    engine.add_rule("p2p.unary.send", "abort", scope="link:*->bob*")
    with pytest.raises(ChaosAbort):
        await engine.inject("p2p.unary.send", scope="link:anyone->bob2")
    await engine.inject("p2p.unary.send", scope="link:bob2->anyone")  # into bob only
    assert engine.stats() == {"p2p.unary.send:abort": 1}

    # legacy substring rule composes: it hits both directions of the peer's links
    engine.clear()
    engine.add_rule("p2p.unary.send", "abort", scope="bob")
    with pytest.raises(ChaosAbort):
        await engine.inject("p2p.unary.send", scope="link:alice->bob")
    with pytest.raises(ChaosAbort):
        await engine.inject("p2p.unary.send", scope="link:bob->alice")


async def test_chaos_link_scope_grammar_survives_colons():
    """The HIVEMIND_CHAOS grammar splits on ':' — a link scope's own colon must
    re-join its key=value field instead of becoming an unknown key."""
    engine = ChaosEngine()
    engine.configure("seed=3;p2p.unary.send:drop:times=2:scope=link:src*->dst*")
    (rule,) = engine.rules
    assert rule.scope == "link:src*->dst*" and rule.times == 2
    with pytest.raises(ChaosDrop):
        await engine.inject("p2p.unary.send", scope="link:src1->dst9")
    await engine.inject("p2p.unary.send", scope="link:dst9->src1")  # wrong direction


async def test_chaos_throttle_is_byte_proportional():
    """ISSUE 11: the `throttle` action models a bandwidth-limited link — sleep
    time scales with the payload's wire size; payload-free points are no-ops."""
    import time as _time

    engine = ChaosEngine()
    engine.add_rule("allreduce.load", "throttle", rate=1_000_000.0)  # 1 MB/s
    started = _time.perf_counter()
    payload = b"\x00" * 100_000  # 0.1 s at 1 MB/s
    returned = await engine.inject("allreduce.load", payload=payload)
    elapsed = _time.perf_counter() - started
    assert returned is payload  # throttle never alters bytes
    assert 0.08 < elapsed < 1.0, elapsed
    started = _time.perf_counter()
    await engine.inject("allreduce.load")  # no payload: no sleep
    assert _time.perf_counter() - started < 0.05
    # grammar: rate is parseable from HIVEMIND_CHAOS specs
    engine.configure("allreduce.reduce:throttle:rate=2e6")
    assert engine.rules[0].rate == 2e6


async def test_chaos_bad_specs_rejected():
    engine = ChaosEngine()
    with pytest.raises(ValueError):
        engine.configure("dht.rpc_find")  # no action
    with pytest.raises(ValueError):
        engine.configure("dht.rpc_find:drop:bogus_key=1")
    with pytest.raises(AssertionError):
        engine.add_rule("dht.rpc_find", "explode")


# ------------------------------------------------------------ DHT churn + chaos


async def _launch_dht_swarm(n_peers: int, **kwargs):
    nodes = [await DHTNode.create(**kwargs)]
    first_maddrs = await nodes[0].get_visible_maddrs()
    rest = await asyncio.gather(
        *(DHTNode.create(initial_peers=[str(m) for m in first_maddrs], **kwargs) for _ in range(n_peers - 1))
    )
    nodes.extend(rest)
    return nodes


@pytest.mark.chaos
async def test_dht_store_get_under_rpc_drops():
    """store/get across a 4-node swarm stays correct with 20% of rpc_store and
    rpc_find calls dropped (seeded), and the blacklists the drops tripped all
    recover once the faults stop."""
    nodes = await _launch_dht_swarm(4, blacklist_time=0.3)
    try:
        CHAOS.clear()
        CHAOS.reseed(7)
        CHAOS.add_rule("dht.rpc_store", "drop", prob=0.2)
        CHAOS.add_rule("dht.rpc_find", "drop", prob=0.2)
        now = get_dht_time()
        # the layer's own retry policy IS the mechanism that makes ops succeed
        # under 20% drops: one attempt may legitimately miss (the only replica
        # holder's rpc_find dropped AND blacklisted it), so retries must outlast
        # the short blacklist window before the holder becomes reachable again
        op_retry = RetryPolicy(
            max_attempts=8, base_delay=0.4, backoff=1.0, jitter="equal", retry_on=(AssertionError,)
        )

        for i in range(8):
            async def store_once(i=i):
                assert await nodes[i % 4].store(f"chaos_key_{i}", f"value_{i}", now + 60)

            await op_retry.execute(lambda i=i: store_once(i))
        for i in range(8):
            async def get_once(i=i):
                result = await nodes[(i + 1) % 4].get(f"chaos_key_{i}", latest=True)
                assert result is not None and result.value == f"value_{i}", f"get {i} failed"

            await op_retry.execute(lambda i=i: get_once(i))
        injected = CHAOS.stats()
        assert injected.get("dht.rpc_store:drop", 0) + injected.get("dht.rpc_find:drop", 0) > 0
        CHAOS.clear()
        # recovery: EVERY node keeps issuing traffic until its tripped breakers
        # are probed back to closed (a breaker only closes on a probe success,
        # and probes only happen when that node itself makes requests)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if all(node.blacklist.all_closed() for node in nodes):
                break
            for j, node in enumerate(nodes):
                if not node.blacklist.all_closed():
                    await node.store(f"recovery_probe_{j}", j, get_dht_time() + 30)
            await asyncio.sleep(0.4)
        for i, node in enumerate(nodes):
            assert node.blacklist.all_closed(), (
                f"node {i} blacklist still tripped: {node.blacklist.tripped_keys()}"
            )
    finally:
        CHAOS.clear()
        await asyncio.gather(*(node.shutdown() for node in nodes))


# ------------------------------------------------------------------------- soak


@pytest.mark.chaos
def test_chaos_soak_smoke():
    """Tier-1-safe soak (seeded, CPU-only, ~30 s): 2 trainers + an MoE pair under
    the full default schedule; steps advance, breakers recover."""
    from hivemind_tpu.hivemind_cli.run_chaos_soak import run_soak

    report = run_soak(n_peers=2, duration=18.0, seed=0, chaos_fraction=0.55, include_moe=True)
    assert report["checks"]["steps_advanced"], report
    assert report["checks"]["steps_advanced_after_chaos"], report
    assert report["checks"]["breakers_recovered"], report
    assert report["checks"]["faults_injected"], report
    assert report["checks"]["no_thread_errors"], report


@pytest.mark.chaos
def test_churn_soak_smoke():
    """Tier-1-safe churn soak (ISSUE 7, ~35 s): 3 trainers under the default
    fault schedule (including state.download corruption/drops), one crash-killed
    mid-chaos — DHT yanked, no shutdown — and restarted against its crash-safe
    checkpoint directory. The verdict requires every restarted peer back at the
    tracker's global epoch and ZERO unverified/corrupt state adoptions."""
    from hivemind_tpu.hivemind_cli.run_chaos_soak import run_soak

    report = run_soak(
        n_peers=3, duration=32.0, seed=0, chaos_fraction=0.5,
        include_moe=False, churn=True, churn_kills=1,
    )
    assert report["checks"]["peers_restarted"], report
    assert report["checks"]["state_recovered"], report
    assert report["digest_failures_adopted"] == 0, report
    assert report["checks"]["digest_failures_adopted_zero"], report
    assert report["checks"]["steps_advanced_after_chaos"], report
    assert report["checks"]["no_thread_errors"], report


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_soak_full():
    """The ISSUE 3 acceptance soak: 4 peers, every named injection point, strict
    recovery checks. Heavy — excluded from tier-1 (also runnable as
    ``python -m hivemind_tpu.hivemind_cli.run_chaos_soak``)."""
    from hivemind_tpu.hivemind_cli.run_chaos_soak import run_soak

    report = run_soak(n_peers=4, duration=60.0, seed=0, chaos_fraction=0.6, include_moe=True)
    assert report["ok"], report


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_soak_full_churn():
    """The ISSUE 7 acceptance soak: chaos + seeded churn; the full verdict
    (including state_recovered and digest_failures_adopted: 0) must hold
    (also runnable as ``python -m hivemind_tpu.hivemind_cli.run_chaos_soak --churn``)."""
    from hivemind_tpu.hivemind_cli.run_chaos_soak import run_soak

    report = run_soak(
        n_peers=4, duration=60.0, seed=0, chaos_fraction=0.6, include_moe=True, churn=True,
    )
    assert report["ok"], report


@pytest.fixture(autouse=True)
def _reset_resilience_state():
    yield
    CHAOS.clear()
    reset_all_boards()
