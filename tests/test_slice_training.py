"""End-to-end multi-host slice TRAINING (examples/slice_training.py): two real
jax.distributed processes form one mesh, take local optax steps, and average
with a plain host-resident swarm peer through SliceAverager rounds. Completes
the two-tier story: the slice both TRAINS over ICI and AVERAGES over the swarm."""

import os
import re
import socket
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLE = os.path.join(_REPO, "examples", "slice_training.py")

_COMPANION = r"""
import sys, time
import numpy as np
maddr = sys.argv[1]
import jax
jax.config.update("jax_platforms", "cpu")
from hivemind_tpu.averaging import DecentralizedAverager
from hivemind_tpu.dht import DHT

dht = DHT(initial_peers=[maddr], start=True)
dim = 16
avg = DecentralizedAverager(
    [np.zeros(dim, np.float32), np.zeros((dim, dim), np.float32)],  # b, w (sorted keys)
    dht, prefix="slice_train_test_params", start=True,
    target_group_size=2, min_matchmaking_time=1.0,
)
joined = 0
deadline = time.monotonic() + 90  # must stay under the parent's communicate timeout
while joined < 2 and time.monotonic() < deadline:
    try:
        if avg.step(timeout=45) is not None:
            joined += 1
            print(f"COMPANION_ROUND_{joined}", flush=True)
    except Exception as e:
        print(f"companion round failed: {e!r}", flush=True)
assert joined >= 1, "companion never joined a slice round"
avg.shutdown(); dht.shutdown()
print("COMPANION_DONE", flush=True)
"""


def test_two_process_slice_trains_and_averages_with_swarm(tmp_path):
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{probe.getsockname()[1]}"
    env = dict(os.environ, PYTHONPATH=os.pathsep.join(
        [_REPO] + [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if p]
    ))
    common = [
        sys.executable, _EXAMPLE, "--platform", "cpu", "--devices_per_proc", "2",
        "--num_processes", "2", "--coordinator", coord,
        "--run_id", "slice_train_test", "--dim", "16", "--batch_size", "8",
        "--steps", "40", "--steps_per_round", "20",
    ]
    procs = [
        subprocess.Popen(
            common + ["--process_id", str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for i in range(2)
    ]
    companion = None
    try:
        # process 0 prints its DHT address once its dht_factory runs
        maddr = None
        deadline = time.monotonic() + 180
        lines = []
        while time.monotonic() < deadline:
            line = procs[0].stdout.readline()
            if not line:
                break
            lines.append(line)
            match = re.search(r"--initial_peers (\S+)", line)
            if match:
                maddr = match.group(1)
                break
        assert maddr, "".join(lines[-30:])

        script = tmp_path / "companion.py"
        script.write_text(_COMPANION)
        companion = subprocess.Popen(
            [sys.executable, str(script), maddr],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )

        outs = ["".join(lines), ""]
        out0, _ = procs[0].communicate(timeout=420)
        outs[0] += out0
        out1, _ = procs[1].communicate(timeout=120)
        outs[1] = out1
        comp_out, _ = companion.communicate(timeout=180)  # > companion's own 90s deadline

        for i, out in enumerate(outs):
            assert procs[i].returncode == 0, f"slice proc {i} failed:\n{out[-3000:]}"
        assert companion.returncode == 0, f"companion failed:\n{comp_out[-3000:]}"

        # at least one swarm round succeeded on the slice side...
        assert "swarm_round_ok=True" in outs[0], outs[0][-2000:]
        # ...the companion reduced with it...
        assert "COMPANION_ROUND_1" in comp_out, comp_out[-2000:]
        # ...and training converged (toy identity regression: loss well below init)
        finals = [
            float(re.search(r"FINAL_LOSS \d ([\d.eE+-]+)", out).group(1)) for out in outs
        ]
        assert all(f < 0.5 for f in finals), finals
        assert abs(finals[0] - finals[1]) < 1e-4, finals  # SPMD: same global loss
    finally:
        for proc in procs + ([companion] if companion else []):
            if proc.poll() is None:
                proc.kill()
