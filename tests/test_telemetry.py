"""The telemetry subsystem (ISSUE 2): registry semantics (labels, histogram
buckets, concurrent increments), the Prometheus exporter scrape round-trip, the
DHT snapshot publish/aggregate path, and a real two-peer run asserting that the
matchmaking / all-reduce / optimizer instrumentation actually advances."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from hivemind_tpu.telemetry import (
    REGISTRY,
    MetricsExporter,
    MetricsRegistry,
    TelemetryPublisher,
    aggregate_swarm_view,
    build_peer_snapshot,
    fetch_swarm_telemetry,
    render_prometheus,
)

from swarm_utils import launch_dht_swarm, shutdown_all


# ------------------------------------------------------------------ registry


def test_counter_labels_and_get_or_create():
    reg = MetricsRegistry()
    c = reg.counter("rpc_calls_total", "calls", ("handler", "side"))
    c.inc(handler="ping", side="server")
    c.inc(2.0, handler="ping", side="server")
    c.labels("find", "client").inc()
    assert c.value(handler="ping", side="server") == 3.0
    assert c.value(handler="find", side="client") == 1.0
    # same name returns the same metric object; wrong type/labels assert
    assert reg.counter("rpc_calls_total", "calls", ("handler", "side")) is c
    with pytest.raises(AssertionError):
        reg.gauge("rpc_calls_total")
    with pytest.raises(AssertionError):
        reg.counter("rpc_calls_total", "calls", ("handler",))


def test_gauge_set_inc_dec():
    reg = MetricsRegistry()
    g = reg.gauge("epoch")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.value() == 4.0


def test_histogram_buckets_are_cumulative():
    reg = MetricsRegistry()
    h = reg.histogram("lat", "latency", ("op",), buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v, op="get")
    child = h.labels(op="get")
    buckets, total, count = child.snapshot()
    assert buckets == [1, 2, 3]  # cumulative: le=0.01 -> 1, le=0.1 -> 2, le=1.0 -> 3
    assert count == 4
    assert abs(total - 5.555) < 1e-9
    text = render_prometheus(reg)
    assert 'lat_bucket{op="get",le="+Inf"} 4' in text
    assert 'lat_count{op="get"} 4' in text


def test_histogram_timer_context():
    reg = MetricsRegistry()
    h = reg.histogram("span", "span", ("what",))
    with h.time(what="sleep"):
        pass
    assert h.labels(what="sleep").count == 1


def test_concurrent_increments_are_lossless():
    reg = MetricsRegistry()
    c = reg.counter("spins_total", "spins", ("worker",))
    h = reg.histogram("spin_lat", "lat")

    def spin(worker):
        child = c.labels(worker)
        hchild = h.labels()
        for _ in range(5000):
            child.inc()
            c.inc(worker="shared")  # un-cached path: exercises get-or-create
            hchild.observe(0.001)

    threads = [threading.Thread(target=spin, args=(str(i),)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value(worker="shared") == 8 * 5000
    assert sum(c.value(worker=str(i)) for i in range(8)) == 8 * 5000
    assert h.labels().count == 8 * 5000


# ------------------------------------------------------------------ exporter


def test_exporter_scrape_roundtrip():
    reg = MetricsRegistry()
    reg.counter("demo_total", "demo", ("kind",)).inc(kind="x")
    reg.gauge("demo_gauge", "demo").set(1.5)
    reg.histogram("demo_seconds", "demo").observe(0.2)
    exporter = MetricsExporter(port=0, registry=reg)
    try:
        base = f"http://127.0.0.1:{exporter.port}"
        body = urllib.request.urlopen(f"{base}/metrics", timeout=5).read().decode()
        assert "# TYPE demo_total counter" in body
        assert 'demo_total{kind="x"} 1' in body
        assert "demo_gauge 1.5" in body
        assert "demo_seconds_count 1" in body
        snapshot = json.loads(urllib.request.urlopen(f"{base}/metrics.json", timeout=5).read())
        assert snapshot["demo_total"]["series"]["kind=x"] == 1
        assert snapshot["demo_seconds"]["series"]["_"]["count"] == 1
        assert urllib.request.urlopen(f"{base}/healthz", timeout=5).read() == b"ok\n"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope", timeout=5)
    finally:
        exporter.shutdown()


def test_prometheus_label_escaping():
    reg = MetricsRegistry()
    reg.counter("esc_total", "esc", ("name",)).inc(name='we"ird\\peer\nid')
    text = render_prometheus(reg)
    assert 'esc_total{name="we\\"ird\\\\peer\\nid"} 1' in text


# ------------------------------------------------------------------ snapshots / aggregation


def test_snapshot_and_swarm_aggregation_without_network():
    reg = MetricsRegistry()
    reg.counter("work_total", "w").inc(7)
    reg.gauge("epoch", "e").set(3)
    reg.histogram("lat", "l").observe(0.5)
    snap_a = build_peer_snapshot(reg, extras={"peer_id": "peerA"})
    snap_b = build_peer_snapshot(reg, extras={"peer_id": "peerB"})
    view = aggregate_swarm_view({"peerA": snap_a, "peerB": snap_b})
    assert view["num_peers"] == 2
    assert view["metrics"]["work_total"]["total"] == 14
    assert view["metrics"]["epoch"]["min"] == view["metrics"]["epoch"]["max"] == 3
    assert view["metrics"]["lat"]["total"] == 2  # histogram counts sum
    assert abs(view["metrics"]["lat"]["sum"] - 1.0) < 1e-9
    assert set(view["peers"]) == {"peerA", "peerB"}


# ------------------------------------------------------------------ end-to-end


def _counter_total(name: str) -> float:
    metric = REGISTRY.get(name)
    if metric is None:
        return 0.0
    total = 0.0
    for _key, child in metric.series():
        total += getattr(child, "value", 0.0) or getattr(child, "count", 0.0)
    return total


def _histogram_count(name: str) -> int:
    metric = REGISTRY.get(name)
    if metric is None:
        return 0
    return sum(child.count for _key, child in metric.series())


def _run_one_moe_batch():
    import asyncio

    from hivemind_tpu.moe.server.runtime import Runtime
    from hivemind_tpu.moe.server.task_pool import TaskPool

    async def run():
        pool = TaskPool(lambda x: x * 2, name="telemetry_e2e_pool", max_batch_size=16)
        runtime = Runtime([pool], stats_report_interval=None)
        runtime.start()
        try:
            await asyncio.wait_for(pool.submit_task(np.ones((2, 3), np.float32)), timeout=10)
        finally:
            runtime.shutdown()

    asyncio.run(run())


def _run_one_slice_epoch_transition():
    import jax
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import SliceOptimizer

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    opt = SliceOptimizer(
        mesh=mesh,
        params={"w": jax.device_put(np.zeros((8, 4), np.float32), sharding)},
        optimizer=optax.sgd(0.1),
        dht_factory=lambda: DHT(start=True),
        run_id="telemetry_e2e_slice",
        target_batch_size=1 << 30,
        batch_size_per_step=1,
    )
    try:
        opt.step({"w": jax.device_put(np.ones((8, 4), np.float32), sharding)}, batch_size=1)
        opt.force_epoch_transition(num_peers=1)
    finally:
        opt.shutdown()


def test_two_peer_run_advances_cross_layer_counters():
    """Two real peers over a real DHT: one averaging round plus progress
    reporting must advance the p2p, DHT, matchmaking, all-reduce and optimizer
    metrics — and the DHT-published snapshots must aggregate into a swarm view."""
    from hivemind_tpu.averaging import DecentralizedAverager
    from hivemind_tpu.optim.progress_tracker import ProgressTracker

    before = {
        "p2p_rpc": _histogram_count("hivemind_p2p_rpc_latency_seconds"),
        "dht_rpc": _histogram_count("hivemind_dht_rpc_latency_seconds"),
        "dht_op": _histogram_count("hivemind_dht_operation_latency_seconds"),
        "matchmaking": _counter_total("hivemind_averaging_matchmaking_rounds_total"),
        "allreduce": _histogram_count("hivemind_averaging_allreduce_phase_seconds"),
    }

    dhts = launch_dht_swarm(2)
    averagers = [
        DecentralizedAverager(
            [np.full(16, float(i), np.float32)], dht, prefix="telemetry_e2e", start=True,
            target_group_size=2, min_matchmaking_time=1.0, request_timeout=1.0,
        )
        for i, dht in enumerate(dhts)
    ]
    trackers = []
    publishers = []
    try:
        controls = [a.step(wait=False, timeout=30) for a in averagers]
        results = [c.result(timeout=60) for c in controls]
        assert all(r is not None for r in results)

        trackers = [ProgressTracker(dht, "telemetry_e2e_run", target_batch_size=1000) for dht in dhts]
        for epoch, tracker in enumerate(trackers):
            tracker.report_local_progress(epoch, 123)

        # every layer moved
        assert _histogram_count("hivemind_p2p_rpc_latency_seconds") > before["p2p_rpc"]
        assert _histogram_count("hivemind_dht_rpc_latency_seconds") > before["dht_rpc"]
        assert _histogram_count("hivemind_dht_operation_latency_seconds") > before["dht_op"]
        assert _counter_total("hivemind_averaging_matchmaking_rounds_total") > before["matchmaking"]
        assert _histogram_count("hivemind_averaging_allreduce_phase_seconds") > before["allreduce"]
        assert REGISTRY.get("hivemind_optim_local_samples_accumulated").value() == 123
        assert REGISTRY.get("hivemind_dht_routing_table_size").value() >= 1

        # layer 5: one MoE runtime batch so the scrape carries all five layers
        _run_one_moe_batch()
        # layer 4 counter: one deterministic slice epoch transition
        _run_one_slice_epoch_transition()

        # acceptance criterion: GET /metrics serves valid exposition with at
        # least one counter sample from every layer
        exporter = MetricsExporter(port=0)
        try:
            page = urllib.request.urlopen(
                f"http://127.0.0.1:{exporter.port}/metrics", timeout=5
            ).read().decode()
        finally:
            exporter.shutdown()
        for counter_sample in (
            'hivemind_p2p_rpc_bytes_total{',               # layer 1
            'hivemind_dht_operation_latency_seconds_count{',  # layer 2
            'hivemind_averaging_matchmaking_rounds_total{',   # layer 3
            'hivemind_optim_epoch_transitions_total{',        # layer 4
            'hivemind_moe_batches_total{',                    # layer 5
        ):
            assert counter_sample in page, f"{counter_sample} missing from scrape"
        for family in (
            "hivemind_p2p_rpc_latency_seconds",
            "hivemind_dht_rpc_latency_seconds",
            "hivemind_optim_local_epoch",
        ):
            assert page.count(f"# TYPE {family}") == 1

        # DHT-published snapshots aggregate into the swarm view
        publishers = [
            TelemetryPublisher(dht, "telemetry_e2e_swarm", interval=30.0, start=False)
            for dht in dhts
        ]
        for publisher in publishers:
            assert publisher.publish_once()
        records = fetch_swarm_telemetry(dhts[0], "telemetry_e2e_swarm")
        assert len(records) == 2
        view = aggregate_swarm_view(records)
        assert view["num_peers"] == 2
        assert "hivemind_p2p_rpc_latency_seconds" in view["metrics"]
    finally:
        for publisher in publishers:
            publisher.shutdown()
        for tracker in trackers:
            tracker.shutdown()
        shutdown_all(averagers, dhts)


def test_moe_runtime_metrics_advance():
    """The Runtime's registry counters replace its private _stats dict."""
    import asyncio

    from hivemind_tpu.moe.server.runtime import Runtime
    from hivemind_tpu.moe.server.task_pool import TaskPool

    before_batches = _counter_total("hivemind_moe_batches_total")
    before_samples = _counter_total("hivemind_moe_samples_total")

    async def run():
        pool = TaskPool(lambda x: x * 2, name="telemetry_pool", max_batch_size=16)
        runtime = Runtime([pool], stats_report_interval=None)
        runtime.start()
        try:
            out = await asyncio.wait_for(pool.submit_task(np.ones((4, 3), np.float32)), timeout=10)
            assert np.allclose(out[0], 2.0)
        finally:
            runtime.shutdown()

    asyncio.run(run())
    assert _counter_total("hivemind_moe_batches_total") == before_batches + 1
    assert _counter_total("hivemind_moe_samples_total") == before_samples + 4
    assert REGISTRY.get("hivemind_moe_batch_latency_seconds").labels(pool="telemetry_pool").count >= 1
