"""Tests for the utils layer — scope mirrors reference tests/test_util_modules.py:
serializer ext types, TimedStorage semantics, streaming split/combine, PerformanceEMA,
asyncio helpers, loop runner (MPFuture equivalent), tensor descriptors, crypto."""

import asyncio
import time

import numpy as np
import pytest

from hivemind_tpu.utils import (
    MSGPackSerializer,
    PerformanceEMA,
    TensorDescriptor,
    BatchTensorDescriptor,
    TimedStorage,
    achain,
    aiter_with_timeout,
    amap_in_executor,
    as_aiter,
    azip,
    combine_from_streaming,
    get_dht_time,
    nested_flatten,
    nested_map,
    nested_pack,
    split_for_streaming,
)
from hivemind_tpu.utils.crypto import Ed25519PrivateKey, RSAPrivateKey
from hivemind_tpu.utils.loop import LoopRunner


def test_msgpack_serializer_roundtrip():
    for obj in [
        {"a": 1, "b": [2, 3], "c": (4, 5, (6,))},
        b"raw bytes",
        "string",
        12345,
        3.14,
        None,
        [1, "two", b"three", (4, 5)],
        {1: "int keys allowed"},
    ]:
        assert MSGPackSerializer.loads(MSGPackSerializer.dumps(obj)) == obj


def test_msgpack_tuple_vs_list_preserved():
    data = MSGPackSerializer.dumps({"t": (1, 2), "l": [1, 2]})
    restored = MSGPackSerializer.loads(data)
    assert restored["t"] == (1, 2) and isinstance(restored["t"], tuple)
    assert restored["l"] == [1, 2] and isinstance(restored["l"], list)


def test_msgpack_ext_serializable():
    @MSGPackSerializer.ext_serializable(0x7A)
    class Pair:
        def __init__(self, a, b):
            self.a, self.b = a, b

        def packb(self):
            return MSGPackSerializer.dumps([self.a, self.b])

        @classmethod
        def unpackb(cls, data):
            return cls(*MSGPackSerializer.loads(data))

        def __eq__(self, other):
            return self.a == other.a and self.b == other.b

    restored = MSGPackSerializer.loads(MSGPackSerializer.dumps({"p": Pair(1, "x")}))
    assert restored["p"] == Pair(1, "x")


def test_timed_storage_basic():
    storage = TimedStorage()
    now = get_dht_time()
    assert storage.store("key", "value", now + 10)
    assert storage.get("key").value == "value"
    assert "key" in storage and len(storage) == 1
    # stale write rejected
    assert not storage.store("key", "older", now + 5)
    assert storage.get("key").value == "value"
    # fresher write wins
    assert storage.store("key", "newer", now + 20)
    assert storage.get("key").value == "newer"
    # expired values vanish
    assert storage.store("fleeting", "gone", now + 0.05)
    time.sleep(0.1)
    assert storage.get("fleeting") is None
    assert "fleeting" not in storage


def test_timed_storage_maxsize_evicts_soonest():
    storage = TimedStorage(maxsize=2)
    now = get_dht_time()
    storage.store("a", 1, now + 100)
    storage.store("b", 2, now + 50)
    storage.store("c", 3, now + 200)
    assert "b" not in storage  # soonest-to-expire evicted
    assert "a" in storage and "c" in storage


def test_timed_storage_top_and_freeze():
    storage = TimedStorage()
    now = get_dht_time()
    storage.store("late", 1, now + 100)
    storage.store("early", 2, now + 10)
    key, entry = storage.top()
    assert key == "early" and entry.value == 2
    storage.store("gone", 3, now + 0.05)
    with storage.freeze():
        time.sleep(0.1)
        assert "gone" in storage  # frozen: no eviction
    assert "gone" not in storage


def test_streaming_split_combine():
    data = bytes(range(256)) * 100
    chunks = list(split_for_streaming(data, chunk_size_bytes=1000))
    assert all(len(c) <= 1000 for c in chunks)
    assert combine_from_streaming(chunks) == data
    assert list(split_for_streaming(b"", 10)) == [b""]


def test_performance_ema():
    ema = PerformanceEMA(alpha=0.5)
    ema.update(10, interval=1.0)  # 10 samples/sec
    assert abs(ema.samples_per_second - 10.0) < 1e-6
    ema.update(10, interval=1.0)
    assert abs(ema.samples_per_second - 10.0) < 1e-6
    with ema.pause():
        time.sleep(0.05)
    ema.update(20, interval=1.0)
    assert ema.samples_per_second > 10.0


def test_nested():
    structure = {"b": [1, (2, 3)], "a": 4}
    flat = list(nested_flatten(structure))
    assert flat == [4, 1, 2, 3]  # dict keys sorted
    packed = nested_pack(flat, structure)
    assert packed == {"a": 4, "b": [1, (2, 3)]}
    doubled = nested_map(lambda x: x * 2, structure)
    assert doubled == {"a": 8, "b": [2, (4, 6)]}


async def test_async_iterators():
    assert [x async for x in as_aiter(1, 2, 3)] == [1, 2, 3]
    assert [x async for x in achain(as_aiter(1), as_aiter(2, 3))] == [1, 2, 3]
    assert [x async for x in azip(as_aiter(1, 2), as_aiter("a", "b", "c"))] == [(1, "a"), (2, "b")]
    squared = [x async for x in amap_in_executor(lambda v: v * v, as_aiter(1, 2, 3))]
    assert squared == [1, 4, 9]

    async def slow_iter():
        yield 1
        await asyncio.sleep(10)
        yield 2

    with pytest.raises(asyncio.TimeoutError):
        _ = [x async for x in aiter_with_timeout(slow_iter(), timeout=0.1)]


def test_loop_runner_sync_and_future():
    runner = LoopRunner("test-loop")

    async def compute(x):
        await asyncio.sleep(0.01)
        return x * 2

    assert runner.run_coroutine(compute(21)) == 42
    future = runner.run_coroutine(compute(10), return_future=True)
    assert future.result(timeout=5) == 20

    async def fail():
        raise ValueError("boom")

    with pytest.raises(ValueError):
        runner.run_coroutine(fail())
    runner.shutdown()


def test_tensor_descriptor():
    arr = np.zeros((4, 8), dtype=np.float32)
    descr = TensorDescriptor.from_array(arr)
    assert descr.shape == (4, 8) and descr.dtype == "float32"
    assert descr.numel == 32 and descr.nbytes == 128
    zeros = descr.make_zeros()
    assert zeros.shape == (4, 8) and zeros.dtype == np.float32

    restored = MSGPackSerializer.loads(MSGPackSerializer.dumps(descr))
    assert restored == descr

    batch = BatchTensorDescriptor.from_array(arr)
    assert batch.shape == (0, 8)
    assert batch.with_batch_size(16).shape == (16, 8)
    assert batch.make_dummy().shape[0] == 3


def test_tensor_descriptor_bfloat16():
    import jax.numpy as jnp

    arr = jnp.zeros((2, 3), dtype=jnp.bfloat16)
    descr = TensorDescriptor.from_array(arr)
    assert descr.dtype == "bfloat16" and descr.itemsize == 2
    zeros = descr.make_zeros("jax")
    assert str(zeros.dtype) == "bfloat16"


@pytest.mark.parametrize("key_type", [Ed25519PrivateKey, RSAPrivateKey])
def test_crypto_sign_verify(key_type):
    key = key_type()
    public = key.get_public_key()
    signature = key.sign(b"hello swarm")
    assert public.verify(b"hello swarm", signature)
    assert not public.verify(b"tampered", signature)
    assert not public.verify(b"hello swarm", b"garbage-signature")
    # serialization round trip
    restored_pub = type(public).from_bytes(public.to_bytes())
    assert restored_pub.verify(b"hello swarm", signature)
    restored_priv = key_type.from_bytes(key.to_bytes())
    assert public.verify(b"again", restored_priv.sign(b"again"))


def test_process_wide_key_singleton():
    k1 = Ed25519PrivateKey.process_wide()
    k2 = Ed25519PrivateKey.process_wide()
    assert k1 is k2


@pytest.mark.slow  # ~24 s (profile_to captures a real XLA trace); trace_span's
# telemetry half is covered sub-second by test_tracing.py::test_unified_trace_span
def test_profiling_hooks():
    """trace_span/profile_to/StepProfiler: XLA profiler integration + throughput EMA."""
    import tempfile
    import jax.numpy as jnp
    from hivemind_tpu.utils.profiling import (
        StepProfiler,
        device_memory_stats,
        profile_to,
        trace_span,
    )

    with tempfile.TemporaryDirectory() as logdir:
        with profile_to(logdir):
            with trace_span("test_region"):
                jnp.ones(8).sum().block_until_ready()
        import os
        assert any(os.scandir(logdir)), "profiler wrote no trace"

    stats = device_memory_stats()
    assert isinstance(stats, dict)  # may be empty on CPU

    prof = StepProfiler(flops_per_token=1e6)
    for _ in range(5):
        prof.step(tokens=100)
    assert prof.total_tokens == 500
    assert prof.tokens_per_second > 0
    assert prof.achieved_flops == prof.tokens_per_second * 1e6
    assert 0 < prof.mfu(1e12) < 1e6
    summary = prof.summary()
    assert summary["total_tokens"] == 500 and summary["achieved_tflops"] is not None
