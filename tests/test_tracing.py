"""Distributed tracing (ISSUE 4): span propagation across a real two-peer
protobuf RPC, ring-buffer eviction, chaos events landing on the correct span,
the ``/trace`` endpoint round-tripping valid Chrome trace JSON, and the
end-to-end attribution demo (a chaos delay injected into one peer's DHT RPC is
visible in that peer's exported trace, under the caller's trace)."""

import asyncio
import json
import time
import urllib.request

import pytest

from hivemind_tpu.resilience import CHAOS, BreakerBoard
from hivemind_tpu.telemetry import (
    RECORDER,
    MetricsExporter,
    SpanRecorder,
    build_peer_snapshot,
    current_span,
    finish_span,
    render_chrome_trace,
    start_span,
    trace,
)
from hivemind_tpu.telemetry.tracing import pack_context, unpack_context


# ------------------------------------------------------------------ span core


def test_span_nesting_parent_child_and_events():
    RECORDER.clear()
    with trace("outer", peer="A") as outer:
        assert current_span() is outer
        outer.add_event("checkpoint", step=3)
        with trace("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
        assert current_span() is outer
    assert current_span() is None
    spans = {s.name: s for s in RECORDER.snapshot()}
    assert set(spans) == {"outer", "inner"}
    assert spans["outer"].end is not None and spans["outer"].duration >= 0
    assert [(n, a) for _t, n, a in spans["outer"].events] == [("checkpoint", {"step": 3})]


def test_detached_span_parents_to_current():
    RECORDER.clear()
    with trace("op") as op:
        detached = start_span("stream")
        assert current_span() is op, "start_span must not install"
        assert detached.parent_id == op.span_id and detached.trace_id == op.trace_id
        finish_span(detached)
    assert any(s.name == "stream" for s in RECORDER.snapshot())


def test_context_wire_format_roundtrip_and_malformed():
    span = start_span("x")
    ctx = unpack_context(pack_context(span))
    assert ctx == (span.trace_id, span.span_id)
    assert unpack_context(None) is None
    assert unpack_context(b"short") is None
    assert unpack_context(b"\x00" * 16) is None  # zero ids = no context
    assert pack_context(None) is None


def test_ring_buffer_evicts_oldest_at_capacity():
    recorder = SpanRecorder(capacity=8)
    for i in range(20):
        span = start_span(f"s{i}")
        finish_span(span, recorder)
    assert len(recorder) == 8
    assert recorder.dropped == 12
    names = [s.name for s in recorder.snapshot()]
    assert names == [f"s{i}" for i in range(12, 20)], "oldest must be evicted first"


def test_slow_span_side_ring_and_threshold():
    recorder = SpanRecorder(capacity=8)
    recorder.slow_threshold = 0.01
    fast = start_span("fast")
    finish_span(fast, recorder)
    slow = start_span("slow")
    slow.add_event("chaos.delay", point="dht.rpc_store")
    time.sleep(0.02)
    finish_span(slow, recorder)
    assert [s.name for s in recorder.slow_spans()] == ["slow"]
    assert "events" in recorder.slow_spans()[0].summary()


def test_tracing_disabled_is_noop():
    from hivemind_tpu.telemetry import tracing

    RECORDER.clear()
    tracing.enabled = False
    try:
        with trace("invisible") as span:
            assert span is None and current_span() is None
        assert start_span("also_invisible") is None
        finish_span(None)  # must not raise
    finally:
        tracing.enabled = True
    assert len(RECORDER) == 0


# ------------------------------------------------------------------ cross-peer


async def _two_peers():
    from hivemind_tpu.p2p import P2P

    alice = await P2P.create()
    bob = await P2P.create()
    for maddr in bob.get_visible_maddrs():
        alice.add_peer_addr(bob.peer_id, maddr.with_peer_id(bob.peer_id))
    return alice, bob


async def test_handler_span_joins_callers_trace_over_real_rpc():
    RECORDER.clear()
    alice, bob = await _two_peers()

    async def handler(request: bytes, context) -> bytes:
        return b"ack:" + request

    await bob.add_protobuf_handler("trace.echo", handler)
    try:
        with trace("client.op", peer=str(alice.peer_id)) as root:
            response = await alice.call_protobuf_handler(bob.peer_id, "trace.echo", b"ping")
        assert response == b"ack:ping"
    finally:
        await alice.shutdown()
        await bob.shutdown()

    spans = {s.name: s for s in RECORDER.snapshot()}
    call = spans["p2p.call:trace.echo"]
    handle = spans["p2p.handle:trace.echo"]
    assert call.trace_id == root.trace_id and call.parent_id == root.span_id
    # the server-side handler span is a CHILD of the remote caller's span:
    # trace context crossed the wire on the OPEN frame
    assert handle.trace_id == root.trace_id
    assert handle.parent_id == call.span_id
    assert handle.attributes["peer"] == str(bob.peer_id)
    assert handle.attributes["remote"] == str(alice.peer_id)


async def test_streaming_rpc_span_propagates_context():
    RECORDER.clear()
    alice, bob = await _two_peers()

    async def handler(requests, context):
        async for message in requests:
            yield b"echo:" + message

    await bob.add_protobuf_handler("trace.stream", handler, stream_input=True, stream_output=True)
    try:
        with trace("client.stream_op", peer=str(alice.peer_id)) as root:
            async def _requests():
                yield b"a"
                yield b"b"

            received = [
                message
                async for message in alice.iterate_protobuf_handler(
                    bob.peer_id, "trace.stream", _requests()
                )
            ]
        assert received == [b"echo:a", b"echo:b"]
    finally:
        await alice.shutdown()
        await bob.shutdown()

    spans = {s.name: s for s in RECORDER.snapshot()}
    stream_span = spans["p2p.stream:trace.stream"]
    handle = spans["p2p.handle:trace.stream"]
    assert stream_span.trace_id == root.trace_id and stream_span.parent_id == root.span_id
    assert handle.trace_id == root.trace_id and handle.parent_id == stream_span.span_id


async def test_chaos_injection_lands_on_the_injected_call_span():
    RECORDER.clear()
    alice, bob = await _two_peers()

    async def handler(request: bytes, context) -> bytes:
        return request

    await bob.add_protobuf_handler("trace.chaos", handler)
    CHAOS.clear()
    CHAOS.add_rule("p2p.unary.send", "delay", delay=0.01, scope=str(alice.peer_id))
    try:
        with trace("client.chaos_op", peer=str(alice.peer_id)):
            await alice.call_protobuf_handler(bob.peer_id, "trace.chaos", b"x")
    finally:
        CHAOS.clear()
        await alice.shutdown()
        await bob.shutdown()

    spans = {s.name: s for s in RECORDER.snapshot()}
    call = spans["p2p.call:trace.chaos"]
    events = [(name, attrs) for _t, name, attrs in call.events or ()]
    assert ("chaos.delay", {"point": "p2p.unary.send"}) in events
    # the fault hit the CALL span, not its parent or the server handler
    assert not spans["client.chaos_op"].events
    assert not spans["p2p.handle:trace.chaos"].events


# ------------------------------------------------------------------ export


def _validate_chrome_trace(doc):
    """A structurally valid Chrome trace-event file (the subset Perfetto and
    chrome://tracing require to load it)."""
    assert isinstance(doc, dict) and isinstance(doc["traceEvents"], list)
    assert doc["traceEvents"], "trace must not be empty"
    for event in doc["traceEvents"]:
        assert isinstance(event["name"], str)
        assert event["ph"] in ("X", "i", "M")
        assert isinstance(event["pid"], int) and isinstance(event["tid"], int)
        if event["ph"] == "X":
            assert isinstance(event["ts"], (int, float)) and isinstance(event["dur"], (int, float))
            assert event["dur"] >= 0
        elif event["ph"] == "i":
            assert isinstance(event["ts"], (int, float))
    return doc


def test_render_chrome_trace_pid_per_peer_and_instants():
    RECORDER.clear()
    with trace("op_a", peer="peerA") as span_a:
        span_a.add_event("chaos.drop", point="dht.rpc_find")
    with trace("op_b", peer="peerB"):
        pass
    doc = _validate_chrome_trace(render_chrome_trace(RECORDER.snapshot()))
    process_names = {
        event["args"]["name"]: event["pid"]
        for event in doc["traceEvents"]
        if event["ph"] == "M" and event["name"] == "process_name"
    }
    assert set(process_names) == {"peer peerA", "peer peerB"}
    by_name = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
    assert by_name["op_a"]["pid"] != by_name["op_b"]["pid"], "one row per peer"
    instants = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert [e["name"] for e in instants] == ["chaos.drop"]
    assert instants[0]["pid"] == by_name["op_a"]["pid"]
    # span args carry ids so parentage is greppable from the JSON alone
    assert by_name["op_a"]["args"]["trace_id"] == f"{span_a.trace_id:016x}"


def test_trace_endpoint_roundtrips_valid_chrome_trace_json():
    RECORDER.clear()
    with trace("http.visible", peer="exporter-test"):
        pass
    exporter = MetricsExporter(port=0)
    try:
        body = urllib.request.urlopen(f"http://127.0.0.1:{exporter.port}/trace", timeout=5).read()
    finally:
        exporter.shutdown()
    doc = _validate_chrome_trace(json.loads(body))
    assert any(e["name"] == "http.visible" for e in doc["traceEvents"])


# ------------------------------------------------------------------ acceptance


def test_e2e_chaos_delay_attribution_across_swarm():
    """ISSUE 4 acceptance: HIVEMIND_CHAOS-style rule injects a delay into one
    DHT RPC on ONE peer of a multi-peer swarm; the exported /trace JSON
    contains a span on that peer, under the caller's trace, carrying the chaos
    event — and the JSON is a valid Chrome trace-event file."""
    from hivemind_tpu.dht import DHT
    from hivemind_tpu.utils.timed_storage import get_dht_time

    first = DHT(start=True)
    maddrs = [str(m) for m in first.get_visible_maddrs()]
    second = DHT(initial_peers=maddrs, start=True)
    third = DHT(initial_peers=maddrs, start=True)
    victim = str(second.peer_id)
    RECORDER.clear()
    CHAOS.configure(f"dht.rpc_store:delay:delay=0.05:scope={victim}")
    exporter = MetricsExporter(port=0)
    try:
        assert second.store("e2e_key", "e2e_value", expiration_time=get_dht_time() + 60)
        CHAOS.clear()
        assert first.get("e2e_key").value == "e2e_value"
        body = urllib.request.urlopen(f"http://127.0.0.1:{exporter.port}/trace", timeout=5).read()
    finally:
        CHAOS.clear()
        exporter.shutdown()
        for dht in (first, second, third):
            dht.shutdown()

    doc = _validate_chrome_trace(json.loads(body))
    events = doc["traceEvents"]
    # 1) the injected delay is visible as an instant event in the trace
    chaos_instants = [e for e in events if e["ph"] == "i" and e["name"] == "chaos.delay"]
    assert chaos_instants, "injected fault must appear in the exported trace"
    owner_span_id = chaos_instants[0]["args"]["span_id"]
    # 2) it sits on the victim peer's dht.store span...
    spans = {e["args"]["span_id"]: e for e in events if e["ph"] == "X"}
    owner = spans[owner_span_id]
    assert owner["name"] == "dht.store" and owner["args"]["peer"] == victim
    # 3) ...whose trace also contains the cross-peer handler span (the caller's
    # trace reached the remote peer through the RPC envelope)
    trace_id = owner["args"]["trace_id"]
    same_trace = [e for e in spans.values() if e["args"]["trace_id"] == trace_id]
    names = {e["name"] for e in same_trace}
    assert "p2p.call:DHTProtocol.rpc_store" in names
    assert "p2p.handle:DHTProtocol.rpc_store" in names
    handle = next(e for e in same_trace if e["name"] == "p2p.handle:DHTProtocol.rpc_store")
    call = next(e for e in same_trace if e["name"] == "p2p.call:DHTProtocol.rpc_store")
    assert handle["args"]["parent_id"] == call["args"]["span_id"]
    # 4) the victim's pid row differs from the remote store target's row
    assert handle["pid"] != owner["pid"]


# ------------------------------------------------------------------ monitor


def test_peer_snapshot_carries_breakers_and_slow_spans():
    RECORDER.clear()
    RECORDER.slow_threshold = 0.005
    board = BreakerBoard("snapshot_test_board", failure_threshold=1, recovery_time=60.0)
    board.register_failure("bad-peer")
    with trace("sluggish.op", peer="me"):
        time.sleep(0.01)
    snapshot = build_peer_snapshot()
    assert snapshot["breakers"]["snapshot_test_board"]["tripped"] == ["bad-peer"]
    assert any(s["name"] == "sluggish.op" for s in snapshot["slow_spans"])
    assert any(s["name"] == "sluggish.op" for s in snapshot["recent_spans"])

    from hivemind_tpu.telemetry.monitor import SwarmMonitor, aggregate_swarm_view

    monitor = SwarmMonitor.__new__(SwarmMonitor)  # no DHT needed for rendering
    snapshot["peer_id"] = "deadbeef"
    view = aggregate_swarm_view({"deadbeef": snapshot})
    report = monitor.render_report(view)
    assert "DEGRADED" in report and "snapshot_test_board" in report and "sluggish.op" in report
    timeline = monitor.render_timeline({"deadbeef": snapshot})
    assert "sluggish.op" in timeline and "trace " in timeline
    board.clear()


def test_unified_trace_span_emits_telemetry_span():
    from hivemind_tpu.utils.profiling import trace_span

    RECORDER.clear()
    with trace_span("unified.step", step=7):
        assert current_span() is not None and current_span().name == "unified.step"
    recorded = [s for s in RECORDER.snapshot() if s.name == "unified.step"]
    assert recorded and recorded[0].attributes["step"] == 7
