"""Compressed expert RPC (ISSUE 10): wire-splice byte identity, per-codec
round-trips through a REAL client→server forward/backward/decode, mixed-
compression swarm interop, and shed/breaker/scorecard behavior under
compression. The serving wire dtype defaults to fp16 (``none`` = bit-identical
fp32); every assertion here pins the contract the default relies on."""

import time
import uuid

import numpy as np
import optax
import pytest

from hivemind_tpu.compression import (
    CompressionType,
    codec_name,
    expert_request_parts,
    expert_response_parts,
    get_codec,
    resolve_activation_codec,
    serialize_tensor,
    split_response_for_wire,
    split_tensor_for_streaming,
)
from hivemind_tpu.proto import runtime_pb2

HID = 16

ALL_CODEC_NAMES = tuple(k.lower() for k in runtime_pb2.CompressionType.keys())


# ------------------------------------------------------------- wire splicers


@pytest.mark.parametrize("name", ALL_CODEC_NAMES)
def test_wire_parts_byte_identical_to_protobuf(name):
    """The hand-spliced scatter-gather frames must be byte-identical to
    protobuf's own SerializeToString for every codec — the receive side parses
    them with the stock generated classes."""
    rng = np.random.RandomState(0)
    codec = resolve_activation_codec(name)
    for array in (
        rng.randn(3, 5).astype(np.float32),
        rng.randn(70000).astype(np.float32),  # multi-chunk when split
        np.array([], np.float32),
        np.float32(2.25),
    ):
        tensor = serialize_tensor(array, codec)
        request = runtime_pb2.ExpertRequest(
            uid="eq.0", tensors=[tensor, tensor], metadata=b"\x00meta"
        )
        assert (
            expert_request_parts("eq.0", [tensor, tensor], b"\x00meta").join()
            == request.SerializeToString()
        )
        # empty uid/metadata are omitted fields, exactly like protobuf
        assert (
            expert_request_parts("", [tensor]).join()
            == runtime_pb2.ExpertRequest(tensors=[tensor]).SerializeToString()
        )
        assert (
            expert_response_parts([tensor]).join()
            == runtime_pb2.ExpertResponse(tensors=[tensor]).SerializeToString()
        )
        # stream chunks: same frames the proto-built chunking emits
        expected_chunks = [
            runtime_pb2.ExpertResponse(tensors=[chunk]).SerializeToString()
            for chunk in split_tensor_for_streaming(tensor, 1024)
        ]
        assert [w.join() for w in split_response_for_wire(tensor, 1024)] == expected_chunks


def test_resolve_activation_codec_knob():
    assert resolve_activation_codec(None).compression_type == CompressionType.NONE
    assert resolve_activation_codec("FLOAT16") is get_codec(CompressionType.FLOAT16)
    assert codec_name(resolve_activation_codec("meanstd_16bit")) == "meanstd_16bit"
    with pytest.raises(ValueError, match="unknown activation compression"):
        resolve_activation_codec("bogus")


# ------------------------------------------------------- real RPC round trips


@pytest.fixture(scope="module")
def serving_pair():
    """One real server + client DHT shared by the round-trip tests (module
    scoped: server startup dominates the suite's runtime)."""
    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe import Server

    server = Server.create(
        expert_uids=["eq.0"], expert_cls="causal_transformer", hidden_dim=HID,
        start=True, optim_factory=lambda: optax.sgd(1e-4),
    )
    client_dht = None
    try:
        time.sleep(1.0)
        client_dht = DHT(
            initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True
        )
        yield server, client_dht
    finally:
        if client_dht is not None:
            client_dht.shutdown()
        server.shutdown()
        server.dht.shutdown()


def _remote(server, client_dht, compression):
    from hivemind_tpu.moe import RemoteExpert
    from hivemind_tpu.moe.expert_uid import ExpertInfo

    return RemoteExpert(
        ExpertInfo("eq.0", server.dht.peer_id, compression), client_dht.node.p2p
    )


@pytest.mark.parametrize("name", ALL_CODEC_NAMES)
def test_codec_forward_roundtrip_through_real_rpc(serving_pair, name):
    """Every codec survives a real rpc_forward: NONE bitwise vs the local
    backend, 16-bit codecs within the documented tolerance, 8-bit codecs
    finite and correlated (they are lossy by design)."""
    server, client_dht = serving_pair
    server.handler.activation_codec = resolve_activation_codec(name)
    expert = _remote(server, client_dht, name)
    rng = np.random.RandomState(1)
    x = rng.randn(2, 6, HID).astype(np.float32)
    [out] = expert.forward_np(x)
    [local] = server.backends["eq.0"].forward(x)
    assert out.shape == local.shape and np.isfinite(out).all()
    if name == "none":
        np.testing.assert_array_equal(out, local)
    elif name in ("float16", "meanstd_16bit"):
        np.testing.assert_allclose(out, local, rtol=2e-2, atol=2e-2)
    else:  # 8-bit: lossy; the signal must still clearly be the same function
        correlation = np.corrcoef(out.ravel(), local.ravel())[0, 1]
        assert correlation > 0.95, correlation


def test_backward_and_decode_roundtrip_none_bitwise(serving_pair):
    """rpc_backward and rpc_decode under the NONE fallback are bit-identical to
    local execution (backward compares gradients BEFORE the optimizer step
    drifts the params; decode compares against a local session manager over the
    same backend)."""
    from hivemind_tpu.moe.server.decode_session import DecodeSessionManager

    server, client_dht = serving_pair
    server.handler.activation_codec = resolve_activation_codec("none")
    expert = _remote(server, client_dht, "none")
    backend = server.backends["eq.0"]
    rng = np.random.RandomState(2)

    # decode: prefill + one continuation, bitwise vs a local manager
    session = uuid.uuid4().hex
    prompt = rng.randn(1, 4, HID).astype(np.float32)
    step = rng.randn(1, 1, HID).astype(np.float32)
    remote_prefill = expert.decode_np(prompt, session, reset=True)
    remote_step = expert.decode_np(step, session)
    local_mgr = DecodeSessionManager({"eq.0": backend}, max_len=256)
    local_prefill = local_mgr.decode("eq.0", "local", prompt, reset=True)
    local_step = local_mgr.decode("eq.0", "local", step, reset=False)
    np.testing.assert_array_equal(remote_prefill, local_prefill)
    np.testing.assert_array_equal(remote_step, local_step)

    # backward: compare gradients against a bit-equal local replay. The remote
    # call ALSO steps the expert's optimizer (by design), so replay locally on
    # a clone of the params first.
    import copy

    x = rng.randn(2, 4, HID).astype(np.float32)
    grad_out = rng.randn(2, 4, HID).astype(np.float32)
    params_before = copy.deepcopy(backend.params)
    opt_before = copy.deepcopy(backend.opt_state)
    [local_grad] = backend.backward(x, grad_out)
    backend.params, backend.opt_state = params_before, opt_before  # rewind the step
    [remote_grad] = expert.backward_np(x, grad_out)
    np.testing.assert_array_equal(remote_grad, local_grad)


def test_fp16_backward_within_tolerance(serving_pair):
    server, client_dht = serving_pair
    server.handler.activation_codec = resolve_activation_codec("float16")
    expert = _remote(server, client_dht, "float16")
    rng = np.random.RandomState(3)
    x = rng.randn(2, 4, HID).astype(np.float32)
    grad_out = rng.randn(2, 4, HID).astype(np.float32)
    import copy

    backend = server.backends["eq.0"]
    params_before = copy.deepcopy(backend.params)
    opt_before = copy.deepcopy(backend.opt_state)
    [local_grad] = backend.backward(x, grad_out)
    backend.params, backend.opt_state = params_before, opt_before
    [remote_grad] = expert.backward_np(x, grad_out)
    np.testing.assert_allclose(remote_grad, local_grad, rtol=5e-2, atol=5e-2)


def test_mixed_compression_swarm_interop(serving_pair):
    """A tensor self-describes its codec on the wire, so an fp16 client against
    a NONE server (and vice versa) interoperates — the designed mixed-swarm /
    rolling-upgrade posture."""
    server, client_dht = serving_pair
    rng = np.random.RandomState(4)
    x = rng.randn(2, 5, HID).astype(np.float32)
    [local] = server.backends["eq.0"].forward(x)

    # fp16 client → NONE server: request rides fp16, response rides fp32
    server.handler.activation_codec = resolve_activation_codec("none")
    fp16_client = _remote(server, client_dht, "float16")
    [out] = fp16_client.forward_np(x)
    np.testing.assert_allclose(out, local, rtol=2e-2, atol=2e-2)

    # NONE client → fp16 server: request exact, response rides fp16
    server.handler.activation_codec = resolve_activation_codec("float16")
    none_client = _remote(server, client_dht, "none")
    [out2] = none_client.forward_np(x)
    np.testing.assert_allclose(out2, local, rtol=2e-2, atol=2e-2)


def test_negotiation_follows_server_advertisement(serving_pair):
    """A client WITHOUT an explicit override negotiates the server's advertised
    codec: from the DHT declaration when present, else via rpc_info."""
    from hivemind_tpu.moe import RemoteExpert
    from hivemind_tpu.moe.expert_uid import ExpertInfo
    from hivemind_tpu.moe.server.dht_handler import get_experts
    from hivemind_tpu.utils.loop import get_loop_runner

    server, client_dht = serving_pair
    server.handler.activation_codec = resolve_activation_codec("float16")

    # DHT path: the periodic declaration carries the wire dtype
    [info] = get_experts(client_dht, ["eq.0"])
    assert info is not None and info.compression == "float16"
    expert = RemoteExpert(info, client_dht.node.p2p)
    codec = get_loop_runner().run_coroutine(expert._wire_codec())
    assert codec.compression_type == CompressionType.FLOAT16

    # rpc_info path: an ExpertInfo without compression falls back to rpc_info
    bare = RemoteExpert(ExpertInfo("eq.0", server.dht.peer_id), client_dht.node.p2p)
    codec = get_loop_runner().run_coroutine(bare._wire_codec())
    assert codec.compression_type == CompressionType.FLOAT16
    assert bare.info["activation_compression"] == "float16"


def test_shed_breaker_scorecard_unchanged_under_compression(serving_pair):
    """Load-shed semantics are orthogonal to the wire dtype: a full bounded
    queue sheds with the typed error across the RPC boundary, trips the expert
    breaker after two sheds, and lands on the client scorecard — all with fp16
    activations active."""
    from hivemind_tpu.moe.client.call_many import EXPERT_BREAKERS
    from hivemind_tpu.telemetry import REGISTRY
    from hivemind_tpu.telemetry.serving import SCORECARDS

    server, client_dht = serving_pair
    server.handler.activation_codec = resolve_activation_codec("float16")
    expert = _remote(server, client_dht, "float16")
    rng = np.random.RandomState(5)
    x = rng.randn(1, 4, HID).astype(np.float32)
    [warm] = expert.forward_np(x)  # route + schema warm, codec active
    assert np.isfinite(warm).all()

    shed_total = REGISTRY.get("hivemind_moe_shed_total")
    sheds_before = shed_total.labels("eq.0_forward").value
    pool = server.handler.forward_pools["eq.0"]
    pool.max_queue_size = 0  # shed everything
    try:
        for _ in range(2):  # EXPERT_BREAKERS failure_threshold == 2
            with pytest.raises(Exception, match="ServerOverloadedError"):
                expert.forward_np(x)
    finally:
        pool.max_queue_size = 1024
    assert shed_total.labels("eq.0_forward").value == sheds_before + 2
    assert "eq.0" in EXPERT_BREAKERS, "sheds did not trip the expert breaker under fp16"
    card = SCORECARDS.card("eq.0")
    assert card is not None and card["sheds"] >= 2
