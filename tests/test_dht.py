"""Tests for the DHT facade, record validators (signature/schema/composite), and the
100-peer-scale behavior (scope: reference tests/test_dht.py, test_dht_crypto.py,
test_dht_schema.py, test_dht_validation.py)."""

import asyncio
import time
from typing import Dict

import pydantic
import pytest

from hivemind_tpu.dht import (
    DHT,
    BytesWithEd25519PublicKey,
    CompositeValidator,
    DHTRecord,
    Ed25519SignatureValidator,
    SchemaValidator,
)
from hivemind_tpu.dht.routing import DHTID
from hivemind_tpu.utils.crypto import Ed25519PrivateKey
from hivemind_tpu.utils.serializer import MSGPackSerializer
from hivemind_tpu.utils.timed_storage import get_dht_time


# ---------------------------------------------------------------- validators (unit)


def make_record(key=b"key", subkey=b"", value=b"value", expiration=None):
    return DHTRecord(key, subkey, value, expiration or get_dht_time() + 30)


def test_signature_validator_roundtrip():
    alice = Ed25519SignatureValidator(Ed25519PrivateKey())
    bob = Ed25519SignatureValidator(Ed25519PrivateKey())

    # unprotected records pass through untouched
    plain = make_record()
    assert alice.validate(plain)
    assert alice.sign_value(plain) == plain.value

    # protected record: only the owner's signature validates
    protected_key = b"some_key_" + alice.local_public_key
    record = make_record(key=protected_key, value=MSGPackSerializer.dumps("payload"))
    signed_value = alice.sign_value(record)
    assert b"[signature:" in signed_value
    signed_record = DHTRecord(record.key, record.subkey, signed_value, record.expiration_time)
    assert alice.validate(signed_record)
    assert bob.validate(signed_record)  # bob verifies using the owner key in the record
    assert alice.strip_value(signed_record) == record.value

    # tampered value must fail
    tampered = DHTRecord(record.key, record.subkey, signed_value.replace(b"payload", b"hacked!"), record.expiration_time)
    assert not alice.validate(tampered)

    # bob cannot forge a record owned by alice
    forged = DHTRecord(record.key, record.subkey, bob.sign_value(record), record.expiration_time)
    assert not alice.validate(forged)
    # protected record without any signature fails
    assert not alice.validate(record)


def test_signature_validator_subkey_protection():
    alice = Ed25519SignatureValidator(Ed25519PrivateKey())
    record = make_record(key=b"shared_dict", subkey=b"peer_" + alice.local_public_key,
                         value=MSGPackSerializer.dumps(123))
    signed = DHTRecord(record.key, record.subkey, alice.sign_value(record), record.expiration_time)
    assert alice.validate(signed)


class ProgressSchema(pydantic.BaseModel):
    epoch: int
    peer_progress: Dict[bytes, float]


def test_schema_validator():
    validator = SchemaValidator(ProgressSchema, allow_extra_keys=False)
    epoch_key = DHTID.generate(source="epoch").to_bytes()

    good = DHTRecord(epoch_key, b"", MSGPackSerializer.dumps(7), get_dht_time() + 30)
    assert validator.validate(good)
    bad_type = DHTRecord(epoch_key, b"", MSGPackSerializer.dumps("not an int"), get_dht_time() + 30)
    assert not validator.validate(bad_type)
    unknown = DHTRecord(DHTID.generate(source="spam").to_bytes(), b"", MSGPackSerializer.dumps(1), get_dht_time() + 30)
    assert not validator.validate(unknown)  # allow_extra_keys=False

    # dict field validates (subkey, value) pairs
    progress_key = DHTID.generate(source="peer_progress").to_bytes()
    good_sub = DHTRecord(progress_key, MSGPackSerializer.dumps(b"peer1"), MSGPackSerializer.dumps(0.5), get_dht_time() + 30)
    assert validator.validate(good_sub)
    bad_sub = DHTRecord(progress_key, MSGPackSerializer.dumps(b"peer1"), MSGPackSerializer.dumps("x"), get_dht_time() + 30)
    assert not validator.validate(bad_sub)


def test_schema_validator_merge():
    class SchemaA(pydantic.BaseModel):
        alpha: int

    class SchemaB(pydantic.BaseModel):
        beta: str

    v = SchemaValidator(SchemaA, allow_extra_keys=False)
    assert v.merge_with(SchemaValidator(SchemaB, allow_extra_keys=False))
    a_key = DHTID.generate(source="alpha").to_bytes()
    b_key = DHTID.generate(source="beta").to_bytes()
    assert v.validate(DHTRecord(a_key, b"", MSGPackSerializer.dumps(1), get_dht_time() + 30))
    assert v.validate(DHTRecord(b_key, b"", MSGPackSerializer.dumps("s"), get_dht_time() + 30))


def test_composite_validator_ordering():
    class ExpectsStripped(pydantic.BaseModel):
        guarded: int

    signature = Ed25519SignatureValidator(Ed25519PrivateKey())
    schema = SchemaValidator(ExpectsStripped, allow_extra_keys=False)
    composite = CompositeValidator([schema, signature])

    key = DHTID.generate(source="guarded").to_bytes() + signature.local_public_key
    record = DHTRecord(key, b"", MSGPackSerializer.dumps(42), get_dht_time() + 30)
    signed_value = composite.sign_value(record)
    signed = DHTRecord(key, b"", signed_value, record.expiration_time)
    # composite must strip the signature before the schema sees the value
    assert composite.validate(signed)
    assert composite.strip_value(signed) == record.value


# ---------------------------------------------------------------- DHT facade


def test_dht_facade_sync_api():
    alice = DHT(start=True)
    bob = DHT(initial_peers=[str(m) for m in alice.get_visible_maddrs()], start=True)
    try:
        assert bob.store("question", "the answer", get_dht_time() + 60)
        result = alice.get("question")
        assert result.value == "the answer"
        # return_future mode
        future = alice.get("question", return_future=True)
        assert future.result(timeout=10).value == "the answer"
        # run_coroutine runs on the loop with node access
        async def count_table(dht, node):
            return len(node.protocol.routing_table)

        assert alice.run_coroutine(count_table) >= 1
        assert str(alice.peer_id) == str(alice.node.peer_id)
    finally:
        bob.shutdown()
        alice.shutdown()


def test_dht_facade_validators_end_to_end():
    validator = Ed25519SignatureValidator(Ed25519PrivateKey())
    intruder_key = Ed25519PrivateKey()
    alice = DHT(start=True, record_validators=[validator])
    bob = DHT(
        initial_peers=[str(m) for m in alice.get_visible_maddrs()],
        start=True,
        record_validators=[Ed25519SignatureValidator(intruder_key)],
    )
    try:
        # protection lives in the subkey (keys are hashed, so markers there are lost):
        # records under alice's subkey can only be written by alice
        owned_subkey = validator.local_public_key
        assert alice.store("progress", 1337, get_dht_time() + 60, subkey=owned_subkey)
        stored = bob.store("progress", 666, get_dht_time() + 120, subkey=owned_subkey)
        assert not stored  # forgery rejected by every storing node
        result = alice.get("progress", latest=True)
        assert result is not None and result.value[owned_subkey].value == 1337
    finally:
        bob.shutdown()
        alice.shutdown()


def test_dht_context_manager():
    with DHT() as dht:
        assert dht.is_alive
        assert dht.store("k", "v", get_dht_time() + 10)
    assert not dht.is_alive
