"""Elastic-swarm churn: a peer joins mid-training and catches up via state download;
a peer dies mid-training and the survivors keep advancing epochs
(scope: reference optimizer.py:655-717 desync detection + load_state_from_peers;
VERDICT r1 item 8 churn test)."""

import threading
import time

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from hivemind_tpu.dht import DHT
from hivemind_tpu.optim import Optimizer

from swarm_utils import launch_dht_swarm


def _toy_problem(seed=0):
    rng = np.random.RandomState(seed)
    true_w = rng.randn(8).astype(np.float32)
    features = rng.randn(256, 8).astype(np.float32)
    targets = features @ true_w

    @jax.jit
    def loss_and_grad(params, x, y):
        return jax.value_and_grad(lambda p: jnp.mean((x @ p["w"] - y) ** 2))(params)

    return features, targets, loss_and_grad


def _make_opt(dht, **overrides):
    options = dict(
        dht=dht, run_id="churn_test", target_batch_size=64,
        params={"w": jnp.zeros(8, jnp.float32)}, optimizer=optax.sgd(0.2),
        batch_size_per_step=16, matchmaking_time=1.5, averaging_timeout=30,
        average_state_every=1, target_group_size=2,
        tracker_opts=dict(min_refresh_period=0.3, default_refresh_period=0.5),
    )
    options.update(overrides)
    return Optimizer(**options)


@pytest.mark.slow  # ~80 s; the sub-minute churn equivalents are
# test_slice_optimizer.py::test_slice_degrades_to_local_grads_and_recovers_on_groupmate_churn
# and test_slice_optimizer.py::test_slice_state_download_fails_over_when_donor_dies_mid_stream
def test_join_catch_up_and_peer_death():
    features, targets, loss_and_grad = _toy_problem()
    dhts = launch_dht_swarm(3)

    stop_all = threading.Event()
    stop_peer1 = threading.Event()
    errors = []
    epochs = {}

    def run_peer(index: int, dht: DHT, stop_event, max_seconds=240.0):
        try:
            opt = _make_opt(dht)
            rng_local = np.random.RandomState(index)
            deadline = time.monotonic() + max_seconds
            # no epoch target: the original peers CANNOT finish before the late
            # joiner arrives, so its catch-up must be a real state download
            while time.monotonic() < deadline and not stop_event.is_set():
                idx = rng_local.choice(len(features), 16)
                _loss, grads = loss_and_grad(opt.params, features[idx], targets[idx])
                opt.step(grads)
                time.sleep(0.25)
            epochs[index] = opt.local_epoch
            opt.shutdown()
        except Exception:
            import traceback

            errors.append((index, traceback.format_exc()))

    threads = [
        threading.Thread(target=run_peer, args=(0, dhts[0], stop_all)),
        threading.Thread(target=run_peer, args=(1, dhts[1], stop_peer1)),
    ]
    for t in threads:
        t.start()
    try:
        # let the original pair make progress, then a third peer joins cold
        time.sleep(12)
        late = _make_opt(dhts[2])
        assert late.local_epoch == 0
        deadline = time.monotonic() + 90
        rng_late = np.random.RandomState(7)
        caught_up = False
        own_steps = 0
        while time.monotonic() < deadline:
            idx = rng_late.choice(len(features), 16)
            _loss, grads = loss_and_grad(late.params, features[idx], targets[idx])
            late.step(grads)
            own_steps += 1
            if late.local_epoch >= 2 and late.local_epoch >= late.tracker.global_epoch - 1:
                caught_up = True
                break
            time.sleep(0.25)
        assert caught_up, (
            f"late joiner stuck at epoch {late.local_epoch} vs swarm {late.tracker.global_epoch}"
        )
        # the jump must come from the swarm: with target_batch 64 and 16/step, the
        # late peer alone could have advanced at most own_steps*16/64 epochs
        assert late.local_epoch > own_steps * 16 / 64 or late.tracker.global_progress.num_peers >= 2, (
            f"late joiner reached epoch {late.local_epoch} alone in {own_steps} steps"
        )
        # params were adopted from the swarm, not still the cold-start zeros
        assert float(jnp.abs(late.params["w"]).sum()) > 0

        # now peer 1 dies mid-training; the swarm must keep advancing
        stop_peer1.set()
        epoch_at_death = late.tracker.global_epoch
        deadline = time.monotonic() + 60
        advanced = False
        while time.monotonic() < deadline:
            idx = rng_late.choice(len(features), 16)
            _loss, grads = loss_and_grad(late.params, features[idx], targets[idx])
            late.step(grads)
            if late.local_epoch >= epoch_at_death + 2:
                advanced = True
                break
            time.sleep(0.25)
        assert advanced, f"swarm stalled at epoch {late.local_epoch} after peer death"
        late.shutdown()
    finally:
        stop_all.set()
        stop_peer1.set()
        for t in threads:
            t.join(timeout=120)
        assert not errors, f"peer failures: {errors}"
        for dht in dhts:
            dht.shutdown()
