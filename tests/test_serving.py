"""Serving-path observability (ISSUE 9): per-request attribution records,
bounded-queue load-shed + breaker feedback, decode-session saturation metrics,
expert scorecards, the ``GET /serving`` endpoint and the ``hivemind-top
--serving`` board — including the two-peer end-to-end test that drives real
``rpc_forward`` / ``rpc_decode`` traffic."""

import asyncio
import json
import sys
import threading
import time
import urllib.request
import uuid

import numpy as np
import optax
import pytest

from hivemind_tpu.telemetry import REGISTRY, MetricsExporter
from hivemind_tpu.telemetry.serving import (
    SCORECARDS,
    SERVING_LEDGER,
    SERVING_SPAN,
    ExpertScorecards,
    ServingLedger,
    is_overload_error,
)
from hivemind_tpu.telemetry.tracing import Span

HID = 16


def _finished_span(name=SERVING_SPAN, duration=0.1, events=(), **attributes) -> Span:
    span = Span(name, attributes=dict(attributes))
    span.start -= duration
    for event_name, event_attrs in events:
        span.add_event(event_name, **event_attrs)
    span.end = time.perf_counter()
    return span


# ---------------------------------------------------------------- ledger units


def test_serving_ledger_assembles_records_from_spans():
    ledger = ServingLedger()
    ledger.on_span(_finished_span(
        duration=0.3, expert="e.0", kind="forward", peer="srv", client="cliA",
        batch=4, occupancy=0.5, pool="e.0_forward",
        queue_wait_s=0.25, assembly_s=0.001, compute_s=0.04, serialize_s=0.002,
    ))
    ledger.on_span(_finished_span(
        duration=0.02, expert="e.1", kind="decode", peer="srv", client="cliB",
        compute_s=0.018,
    ))
    # a non-serving span is ignored (one failed name compare)
    ledger.on_span(_finished_span(name="allreduce.round", duration=9.0))
    records = ledger.records()
    assert len(records) == 2
    first = records[0]
    assert first["expert"] == "e.0" and first["kind"] == "forward"
    assert first["client"] == "cliA" and first["batch"] == 4
    assert first["queue_wait_s"] == pytest.approx(0.25)
    assert first["compute_s"] == pytest.approx(0.04)
    assert first["occupancy"] == 0.5 and first["pool"] == "e.0_forward"
    assert first["queue_wait_s"] > first["compute_s"]  # decomposition readable

    experts = ledger.expert_stats()
    assert set(experts) == {"e.0", "e.1"}
    assert experts["e.0"]["requests"] == 1 and "p95_s" in experts["e.0"]
    clients = ledger.client_stats()
    assert clients["cliA"]["requests"] == 1 and clients["cliB"]["requests"] == 1
    # slowest exemplars: the 0.3 s forward leads
    assert ledger.slowest()[0]["expert"] == "e.0"
    summary = ledger.summary()
    assert summary["requests"] == 2 and summary["sheds"] == 0
    assert summary["phases"]["queue_wait_s"]["p95"] >= 0.25
    assert summary["batch_occupancy"]["mean"] == pytest.approx(0.5)
    snapshot = ledger.snapshot()
    assert snapshot["totals"]["requests"] == 2
    assert "e.0" in snapshot["experts"]


def test_serving_ledger_classifies_sheds_and_errors():
    ledger = ServingLedger()
    ledger.on_span(_finished_span(
        duration=0.001, expert="e.0", kind="forward", client="cliA",
        events=[("error", {"type": "ServerOverloadedError"})],
    ))
    ledger.on_span(_finished_span(
        duration=0.001, expert="e.0", kind="forward", client="cliA",
        events=[("error", {"type": "KeyError"})],
    ))
    summary = ledger.summary()
    assert summary["requests"] == 2 and summary["errors"] == 2 and summary["sheds"] == 1
    assert summary["experts"]["e.0"]["sheds"] == 1
    assert ledger.records()[0]["error"] == "ServerOverloadedError"


def test_serving_ledger_bounds_client_cardinality():
    """Client ids are remote-controlled: cycling identities must not grow the
    table without bound."""
    ledger = ServingLedger(max_clients=8)
    for index in range(50):
        ledger.on_span(_finished_span(expert="e.0", client=f"cli-{index}"))
    assert len(ledger.client_stats()) <= 8


def test_scorecards_classify_outcomes():
    cards = ExpertScorecards()
    cards.record("e.0", 0.01, ok=True)
    cards.record("e.0", 0.02, ok=True, kind="backward")
    cards.record("e.0", 0.5, ok=False, error=RuntimeError("ServerOverloadedError: full"))
    cards.record("e.0", 1.0, ok=False, error=asyncio.CancelledError())
    cards.record("e.0", 0.1, ok=False, error=ValueError("boom"))
    card = cards.card("e.0")
    assert card["requests"] == 5 and card["ok"] == 2
    assert card["sheds"] == 1 and card["timeouts"] == 1 and card["failures"] == 1
    assert card["success_rate"] == pytest.approx(0.4)
    assert card["p95_s"] >= card["p50_s"] > 0
    assert card["kinds"] == {"forward": 4, "backward": 1}
    assert is_overload_error(RuntimeError("ServerOverloadedError: full"))
    assert not is_overload_error(ValueError("fine"))


# ---------------------------------------------------------------- pool units


async def test_task_pool_deque_semantics_and_phase_stamps():
    from hivemind_tpu.moe.server.task_pool import TaskPool

    pool = TaskPool(lambda x: [x * 2], "unit_pool", max_batch_size=8)
    inputs = [np.full((2, 4), float(i), np.float32) for i in range(3)]
    submits = [asyncio.create_task(pool.submit_task(x)) for x in inputs]
    await asyncio.sleep(0.01)
    assert pool.queue_size == 3
    assert pool.priority < float("inf")

    batch = pool.pop_batch()
    # oldest-first drain (deque popleft), all three fit in max_batch_size=8
    assert [float(t.args[0][0, 0]) for t in batch] == [0.0, 1.0, 2.0]
    assert all(t.popped_pc is not None for t in batch)
    assert pool.queue_size == 0 and pool.priority == float("inf")

    pool.process_batch(batch)
    results = await asyncio.gather(*submits)
    for x, [out] in zip(inputs, results):
        np.testing.assert_array_equal(out, x * 2)
    # phase stamps: compute/assembly/occupancy shared per batch
    assert all(t.compute_s is not None and t.assembly_s is not None for t in batch)
    assert all(t.occupancy == pytest.approx(6 / 8) for t in batch)


async def test_task_pool_bounded_queue_sheds():
    from hivemind_tpu.moe.server.task_pool import ServerOverloadedError, TaskPool

    pool = TaskPool(lambda x: [x], "shed_pool", max_batch_size=4, max_queue_size=1)
    shed_counter = REGISTRY.get("hivemind_moe_shed_total").labels("shed_pool")
    sheds_before = shed_counter.value
    first = asyncio.create_task(pool.submit_task(np.zeros((1, 2), np.float32)))
    await asyncio.sleep(0.01)
    with pytest.raises(ServerOverloadedError, match="shed"):
        await pool.submit_task(np.zeros((1, 2), np.float32))
    assert shed_counter.value == sheds_before + 1
    # depth gauge sampled on submit: the queued (not shed) task is visible
    assert REGISTRY.get("hivemind_moe_pool_queue_depth").labels("shed_pool").value == 1
    batch = pool.pop_batch()
    pool.process_batch(batch)
    await first


async def test_process_batch_validates_output_leading_dim():
    """Satellite: a process_func returning the wrong leading batch dim used to
    silently mis-slice per-task outputs — now the whole batch fails loudly."""
    from hivemind_tpu.moe.server.task_pool import TaskPool

    pool = TaskPool(lambda x: [x[:1]], "bad_pool", max_batch_size=8)
    submits = [
        asyncio.create_task(pool.submit_task(np.zeros((2, 3), np.float32)))
        for _ in range(2)
    ]
    await asyncio.sleep(0.01)
    batch = pool.pop_batch()
    with pytest.raises(ValueError, match="leading") as excinfo:
        pool.process_batch(batch)
    assert "4 samples" in str(excinfo.value)  # descriptive: expected batch size named
    pool.fail_batch(batch, excinfo.value)  # what the Runtime does with the raise
    for submit in submits:
        with pytest.raises(ValueError, match="mis-slice"):
            await submit


# ------------------------------------------------------- decode session limits


def _decode_backend(uid="lim.0"):
    from hivemind_tpu.moe import ModuleBackend
    from hivemind_tpu.moe.server.layers.common import CausalTransformerExpert

    module = CausalTransformerExpert(hidden_dim=HID, num_heads=4)
    return {uid: ModuleBackend(
        uid, module, optimizer=optax.sgd(1e-3),
        sample_input=np.zeros((1, 4, HID), np.float32), max_batch_size=8,
    )}


def test_decode_session_cap_eviction_and_counters():
    """Satellite: decode_max_sessions overflow was untested. The LRU cap must
    evict the oldest session (continuations on it then raise), and the new
    occupancy/eviction metrics must record it."""
    from hivemind_tpu.moe.server.decode_session import DecodeSessionManager

    manager = DecodeSessionManager(_decode_backend(), max_len=32, max_sessions=2)
    evictions = REGISTRY.get("hivemind_moe_decode_session_evictions_total")
    cap_before = evictions.labels("cap").value
    rng = np.random.RandomState(0)
    prompts = {name: rng.randn(1, 3, HID).astype(np.float32) for name in ("s1", "s2", "s3")}
    for name in ("s1", "s2", "s3"):
        manager.decode("lim.0", name, prompts[name], reset=True)
        time.sleep(0.002)  # distinct last_used ordering
    # the cap (2) is enforced on the next decode's eviction sweep: s1 (oldest) dies
    step = rng.randn(1, 1, HID).astype(np.float32)
    manager.decode("lim.0", "s3", step, reset=False)
    assert set(k[1] for k in manager._sessions) == {"s2", "s3"}
    assert evictions.labels("cap").value == cap_before + 1
    assert REGISTRY.get("hivemind_moe_decode_sessions").value() == 2
    assert REGISTRY.get("hivemind_moe_decode_session_occupancy").value() == pytest.approx(1.0)
    with pytest.raises(KeyError, match="reset=True"):
        manager.decode("lim.0", "s1", step, reset=False)


def test_decode_session_ttl_eviction_and_reset_semantics():
    from hivemind_tpu.moe.server.decode_session import DecodeSessionManager

    manager = DecodeSessionManager(
        _decode_backend(), max_len=32, max_sessions=8, session_ttl=0.1
    )
    evictions = REGISTRY.get("hivemind_moe_decode_session_evictions_total")
    resets = REGISTRY.get("hivemind_moe_decode_session_resets_total")
    ttl_before = evictions.labels("ttl").value
    resets_before = resets.value()
    rng = np.random.RandomState(1)
    prompt = rng.randn(1, 4, HID).astype(np.float32)

    out_first = manager.decode("lim.0", "ttl-session", prompt, reset=True)
    session = manager._sessions[("lim.0", "ttl-session")]
    assert session.index == 4
    # reset on the SAME id rebuilds the cache from scratch: index restarts and
    # the prefill output is bit-identical to the first (deterministic)
    out_reset = manager.decode("lim.0", "ttl-session", prompt, reset=True)
    np.testing.assert_array_equal(out_first, out_reset)
    assert manager._sessions[("lim.0", "ttl-session")].index == 4
    assert resets.value() == resets_before + 2

    time.sleep(0.15)  # past the TTL
    manager.decode("lim.0", "fresh", prompt, reset=True)  # sweep runs here
    assert ("lim.0", "ttl-session") not in manager._sessions
    # >=: the first decode's jit compile can itself exceed the tiny TTL, making
    # an earlier sweep evict once already — at least the final eviction counted
    assert evictions.labels("ttl").value >= ttl_before + 1
    steps = REGISTRY.get("hivemind_moe_decode_steps_total")
    assert steps.labels("direct").value >= 3


# ------------------------------------------------------------------ end-to-end


def test_two_peer_serving_attribution_shed_breaker_and_board(capsys):
    """The acceptance test: real rpc_forward/rpc_decode traffic between two DHT
    peers. Asserts (a) a ServingLedger record decomposes queue-wait vs compute
    with queue-wait dominating when the pool is artificially stalled, (b) a
    shed request increments hivemind_moe_shed_total AND trips the client-side
    expert breaker, (c) GET /serving and `hivemind-top --serving --frames 1
    --no-ansi` render the board."""
    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe import RemoteExpert, RemoteSequential, Server
    from hivemind_tpu.moe.client.call_many import EXPERT_BREAKERS
    from hivemind_tpu.moe.expert_uid import ExpertInfo
    from hivemind_tpu.telemetry import TelemetryPublisher
    from hivemind_tpu.telemetry.tracing import RECORDER

    SERVING_LEDGER.clear()
    SCORECARDS.clear()
    server = Server.create(
        expert_uids=["sobs.0", "sobs.1"], expert_cls="causal_transformer",
        hidden_dim=HID, start=True, optim_factory=lambda: optax.sgd(1e-4),
    )
    client_dht = None
    try:
        time.sleep(1.0)
        client_dht = DHT(initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True)
        rng = np.random.RandomState(0)

        # --- rpc_decode traffic through a real KV session -------------------
        pipe = RemoteSequential(client_dht, "sobs.", 1)
        session = uuid.uuid4().hex
        hidden = rng.randn(1, 5, HID).astype(np.float32)
        pipe.decode_step(hidden[:, :4], session, reset=True)
        pipe.decode_step(hidden[:, 4:5], session)
        decode_records = [r for r in SERVING_LEDGER.records() if r["kind"] == "decode"]
        assert decode_records, SERVING_LEDGER.records()
        assert decode_records[-1]["expert"] == "sobs.0"
        assert decode_records[-1]["client"] == str(client_dht.peer_id)
        assert decode_records[-1]["compute_s"] > 0
        # the record joined the CALLER's trace: the client-side p2p.call span
        # of an rpc_decode shares its trace id with a serving record
        client_traces = {
            f"{span.trace_id:016x}" for span in RECORDER.snapshot()
            if span.name == "p2p.call:ConnectionHandler.rpc_decode"
        }
        assert any(r["trace"] in client_traces for r in decode_records)

        # --- rpc_forward with an artificially stalled runtime ---------------
        # occupy the single drain executor with a slow batch on sobs.1, then
        # request sobs.0: its task sits in the queue behind the slow batch, so
        # queue-wait must dominate its decomposition
        slow_pool = server.handler.forward_pools["sobs.1"]
        original_process = slow_pool.process_func

        def slow_process(*args):
            time.sleep(1.0)
            return original_process(*args)

        slow_pool.process_func = slow_process
        info0 = ExpertInfo("sobs.0", server.dht.peer_id)
        info1 = ExpertInfo("sobs.1", server.dht.peer_id)
        expert0 = RemoteExpert(info0, client_dht.node.p2p)
        expert1 = RemoteExpert(info1, client_dht.node.p2p)
        x = rng.randn(1, 4, HID).astype(np.float32)

        slow_thread = threading.Thread(target=lambda: expert1.forward_np(x))
        slow_thread.start()
        time.sleep(0.4)  # let the slow batch reach the device executor
        expert0.forward_np(x)  # queues behind the 1.0 s batch
        slow_thread.join(timeout=15)
        stalled = [
            r for r in SERVING_LEDGER.records()
            if r["kind"] == "forward" and r["expert"] == "sobs.0"
        ]
        assert stalled, SERVING_LEDGER.records()
        record = stalled[-1]
        assert record["queue_wait_s"] > 0.3, record
        assert record["queue_wait_s"] > record["compute_s"], record
        slow_pool.process_func = original_process

        # --- load-shed: bounded queue -> typed error -> client breaker ------
        shed_total = REGISTRY.get("hivemind_moe_shed_total")
        sheds_before = shed_total.labels("sobs.0_forward").value
        server.handler.forward_pools["sobs.0"].max_queue_size = 0  # shed everything
        for _ in range(2):  # EXPERT_BREAKERS failure_threshold == 2
            with pytest.raises(Exception, match="ServerOverloadedError"):
                expert0.forward_np(x)
        assert shed_total.labels("sobs.0_forward").value == sheds_before + 2
        assert "sobs.0" in EXPERT_BREAKERS, "sheds did not trip the expert breaker"
        card = SCORECARDS.card("sobs.0")
        assert card is not None and card["sheds"] >= 2
        server.handler.forward_pools["sobs.0"].max_queue_size = 1024
        shed_records = [r for r in SERVING_LEDGER.records() if r.get("error")]
        assert any(r["error"] == "ServerOverloadedError" for r in shed_records)

        # --- GET /serving ----------------------------------------------------
        exporter = MetricsExporter(port=0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{exporter.port}/serving", timeout=5
            ).read()
        finally:
            exporter.shutdown()
        doc = json.loads(body)
        assert doc["summary"]["requests"] >= 4
        assert doc["summary"]["sheds"] >= 2
        assert "sobs.0" in doc["experts"]
        assert "sobs.0" in doc["scorecards"]
        assert doc["records"][0]["client"] == str(client_dht.peer_id)

        # --- hivemind-top --serving --frames 1 --no-ansi ---------------------
        from hivemind_tpu.hivemind_cli import run_top

        publisher = TelemetryPublisher(
            server.dht, "serving_test_telemetry", interval=60.0, start=False
        )
        assert publisher.publish_once()
        assert "serving" in publisher.last_published, publisher.last_published.keys()
        argv_before = sys.argv
        sys.argv = [
            "hivemind-top",
            "--initial_peers", *[str(m) for m in server.dht.get_visible_maddrs()],
            "--key", "serving_test_telemetry",
            "--frames", "1", "--no-ansi", "--serving",
        ]
        try:
            run_top.main()
        finally:
            sys.argv = argv_before
        out = capsys.readouterr().out
        assert "serving board" in out, out
        assert "sobs.0" in out, out
        assert "SHEDS" in out or "slowest requests" in out, out
    finally:
        if client_dht is not None:
            client_dht.shutdown()
        server.shutdown()
        server.dht.shutdown()


# ------------------------------------------------------------- render fallback


def test_serving_board_renders_and_survives_malformed_snapshot():
    """Pure render: QPS delta column, saturation lines, malformed peer row."""
    from hivemind_tpu.hivemind_cli.run_top import render_serving_board

    now = time.time()
    records = {
        "peerA": {
            "serving": {
                "totals": {"requests": 120, "errors": 3, "sheds": 2},
                "experts": {
                    "lb.0": {"requests": 100, "p95_s": 0.04, "sheds": 2},
                    "lb.1": {"requests": 20, "p95_s": 0.01},
                },
                "saturation": {
                    "queue_depth": {"pool=lb.0_forward": 7},
                    "runtime_utilization": {"_": 0.93},
                    "decode_session_occupancy": {"_": 0.5},
                    "sheds": 2,
                },
                "scorecards": {
                    "far.9": {"requests": 10, "success_rate": 0.5, "timeouts": 3,
                              "sheds": 2, "failures": 0},
                },
                "slowest": [
                    {"expert": "lb.0", "kind": "forward", "client": "cliX",
                     "total_s": 0.31, "queue_wait_s": 0.28, "compute_s": 0.02},
                ],
            },
        },
        "peerEvil": {"serving": {"experts": "nope", "saturation": 3}},
        "peerWeird": {"serving": ["not", "a", "dict"]},  # present but unparseable
    }
    board, state = render_serving_board(records, now=now, ansi=False)
    assert "serving board" in board and "lb.0" in board
    assert "SHEDS 2" in board and "runtime util 93%" in board
    assert "decode sessions 50% full" in board
    assert "far.9" in board and "ok=50%" in board
    assert "queue_wai" in board or "queue" in board  # phase decomposition shown
    assert ("peerA", "lb.0") in state
    # second frame: QPS from the request-count delta (100 -> 150 over 10 s)
    records["peerA"]["serving"]["experts"]["lb.0"]["requests"] = 150
    board2, _ = render_serving_board(
        records,
        prev_requests={key: (value[0], value[1] - 10.0) for key, value in state.items()},
        now=now, ansi=False,
    )
    assert "5.0" in board2  # 50 requests over 10 s
    # the malformed peers get flagged rows, never a dead board — including the
    # non-dict section and a peer whose parse failed mid-way (whose partial
    # rows must be rolled back, not shown alongside the malformed flag)
    assert board.count("<malformed serving section>") == 2, board
    from hivemind_tpu.telemetry.serving import collect_swarm_serving

    data = collect_swarm_serving(records)
    assert sorted(data["malformed"]) == ["peerEvil", "peerWeird"]
    assert all(peer == "peerA" for peer, _uid, _stats in data["experts"])

    from hivemind_tpu.telemetry.monitor import SwarmMonitor, aggregate_swarm_view

    monitor = SwarmMonitor.__new__(SwarmMonitor)
    monitor.publish_interval = 30.0
    view = aggregate_swarm_view(
        {"peerA": {"time": now, "metrics": {}, **records["peerA"]}}
    )
    report = monitor.render_report(view)
    assert "serving board" in report and "lb.0" in report
    assert "slowest requests" in report


def test_shrink_prefers_serving_section_over_metric_label_detail():
    """Regression (seen in-suite): a label-bloated registry used to push the
    serving/ledger sections out of the DHT snapshot budget while full per-label
    metric series survived. The shrink now compacts metric families (totals
    preserved swarm-wide) BEFORE dropping attribution sections."""
    from hivemind_tpu.telemetry.monitor import _shrink_to_fit
    from hivemind_tpu.utils.serializer import MSGPackSerializer

    metrics = {
        f"hivemind_bloated_family_{i}": {
            "type": "counter",
            "series": {f"peer=verylongpeeridentifier-{j:04d}": float(j) for j in range(200)},
        }
        for i in range(12)
    }
    serving = {
        "totals": {"requests": 10, "errors": 0, "sheds": 1},
        "experts": {"lb.0": {"requests": 10, "p95_s": 0.05, "sheds": 1}},
    }
    snapshot = {"time": 0.0, "metrics": metrics, "serving": serving,
                "ledger": {"stragglers": {"peerX": {"rounds_slowest": 2, "excess_s": 0.5}}}}
    assert len(MSGPackSerializer.dumps(snapshot)) > 48 * 1024  # genuinely oversized
    shrunk = _shrink_to_fit(dict(snapshot))
    assert len(MSGPackSerializer.dumps(shrunk)) <= 48 * 1024
    assert shrunk["serving"]["experts"]["lb.0"]["sheds"] == 1
    assert shrunk["ledger"]["stragglers"]["peerX"]["rounds_slowest"] == 2
    # label detail paid the bill: families compacted to one aggregate series
    assert any(f.get("compacted") for f in shrunk["metrics"].values())
