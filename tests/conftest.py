"""Test harness: all tests run on a virtual 8-device CPU mesh so multi-chip sharding
logic is exercised without TPU hardware (the driver separately dry-runs the multichip
path; see __graft_entry__.py). Must set env BEFORE jax import."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Force the CPU backend via jax.config, NOT the env var: this image's sitecustomize
# registers a TPU-tunnel plugin at interpreter startup and force-sets
# jax_platforms="axon,cpu", which would make every test run claim the real TPU chip
# (and hang whenever the tunnel is busy/wedged). Tests must be hermetic: an 8-device
# virtual CPU mesh. Initializing the backend here also makes the suite immune to a
# separate cryptography-keygen/plugin-discovery deadlock observed on this image.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.devices()

import asyncio  # noqa: E402
import gc  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


def pytest_pyfunc_call(pyfuncitem):
    """Native asyncio test support (pytest-asyncio is not installed on this image):
    `async def` tests run under asyncio.run with a fresh loop."""
    if inspect.iscoroutinefunction(pyfuncitem.obj):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(pyfuncitem.obj(**kwargs))
        return True
    return None


@pytest.fixture(autouse=True)
def cleanup_children():
    """Reset process-wide singletons between tests (reference tests/conftest.py:14-33)."""
    yield
    import os

    from hivemind_tpu.resilience import CHAOS, reset_all_boards
    from hivemind_tpu.telemetry import watchdog as telemetry_watchdog
    from hivemind_tpu.telemetry.ledger import LEDGER
    from hivemind_tpu.telemetry.serving import SCORECARDS, SERVING_LEDGER
    from hivemind_tpu.telemetry.tracing import RECORDER
    from hivemind_tpu.utils.crypto import Ed25519PrivateKey

    CHAOS.clear()  # a test's armed fault rules must never leak into the next test
    reset_all_boards()  # module-level breaker boards (e.g. moe EXPERT_BREAKERS) too
    RECORDER.clear()  # one test's spans must not satisfy another's assertions
    RECORDER.slow_threshold = float(os.environ.get("HIVEMIND_SLOW_SPAN_S", "10.0"))
    LEDGER.clear()  # one test's round records must not satisfy another's assertions
    SERVING_LEDGER.clear()  # serving records + expert scorecards likewise
    SCORECARDS.clear()
    telemetry_watchdog.shutdown_all()  # watchdog threads re-arm with the next loop owner
    Ed25519PrivateKey.reset_process_wide()
    gc.collect()


@pytest.fixture
def event_loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()
