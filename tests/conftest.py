"""Test harness: all tests run on a virtual 8-device CPU mesh so multi-chip sharding
logic is exercised without TPU hardware (the driver separately dry-runs the multichip
path; see __graft_entry__.py). Must set env BEFORE jax import."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Force the CPU backend via jax.config, NOT the env var: this image's sitecustomize
# registers a TPU-tunnel plugin at interpreter startup and force-sets
# jax_platforms="axon,cpu", which would make every test run claim the real TPU chip
# (and hang whenever the tunnel is busy/wedged). Tests must be hermetic: an 8-device
# virtual CPU mesh. Initializing the backend here also makes the suite immune to a
# separate cryptography-keygen/plugin-discovery deadlock observed on this image.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.devices()

import asyncio  # noqa: E402
import gc  # noqa: E402
import inspect  # noqa: E402
import threading  # noqa: E402
import time  # noqa: E402

import pytest  # noqa: E402


def _run_async_test(func, kwargs, allow_task_leaks: bool) -> None:
    """asyncio sanitizer (ISSUE 16 satellite): run the test on a fresh loop and
    fail it when it leaks pending tasks or lets a task exception rot
    unretrieved — the runtime twin of the ``fire-and-forget`` lint rule.
    Opt out with ``@pytest.mark.allow_task_leaks`` (e.g. for tests that
    deliberately abandon a wedged peer)."""
    unhandled = []
    leaked = []
    loop = asyncio.new_event_loop()
    loop.set_exception_handler(lambda _loop, context: unhandled.append(context))
    asyncio.set_event_loop(loop)

    async def _main():
        try:
            await func(**kwargs)
        finally:
            current = asyncio.current_task()
            leaked.extend(
                task for task in asyncio.all_tasks() if task is not current and not task.done()
            )
            for task in leaked:
                task.cancel()
            if leaked:
                # reap them even when allowed, so nothing pollutes the next test
                await asyncio.wait(leaked, timeout=3.0)

    try:
        loop.run_until_complete(_main())
        loop.run_until_complete(loop.shutdown_asyncgens())
        loop.run_until_complete(loop.shutdown_default_executor())
    finally:
        asyncio.set_event_loop(None)
        loop.close()
    # a failed task that was never awaited reports "exception was never
    # retrieved" via the loop exception handler from Task.__del__ — force it now
    del func, kwargs
    gc.collect()

    if allow_task_leaks:
        return
    problems = []
    if leaked:
        names = sorted(task.get_name() for task in leaked)
        problems.append(
            f"test left {len(leaked)} pending task(s) on the loop: {names} — "
            f"await/cancel them (or mark the test @pytest.mark.allow_task_leaks)"
        )
    for context in unhandled:
        message = context.get("message", "")
        exception = context.get("exception")
        problems.append(
            f"unhandled asyncio error: {message or 'exception'}: {exception!r} "
            f"(task={context.get('task') or context.get('future')})"
        )
    if problems:
        pytest.fail("asyncio sanitizer: " + "\n".join(problems))


def pytest_pyfunc_call(pyfuncitem):
    """Native asyncio test support (pytest-asyncio is not installed on this image):
    `async def` tests run on a fresh sanitized loop."""
    if inspect.iscoroutinefunction(pyfuncitem.obj):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        allow = pyfuncitem.get_closest_marker("allow_task_leaks") is not None
        _run_async_test(pyfuncitem.obj, kwargs, allow)
        return True
    return None


@pytest.fixture(autouse=True)
def cleanup_children(request):
    """Reset process-wide singletons between tests (reference tests/conftest.py:14-33)."""
    thread_baseline = {thread.ident for thread in threading.enumerate()}
    yield
    import os

    from hivemind_tpu.resilience import CHAOS, reset_all_boards
    from hivemind_tpu.telemetry import watchdog as telemetry_watchdog
    from hivemind_tpu.telemetry.blackbox import disarm_blackbox
    from hivemind_tpu.telemetry.device import reset_device_telemetry
    from hivemind_tpu.telemetry.ledger import LEDGER
    from hivemind_tpu.telemetry.serving import SCORECARDS, SERVING_LEDGER
    from hivemind_tpu.telemetry.tracing import RECORDER
    from hivemind_tpu.utils.crypto import Ed25519PrivateKey

    disarm_blackbox()  # a test's armed spool must never capture the next test's spans
    CHAOS.clear()  # a test's armed fault rules must never leak into the next test
    reset_all_boards()  # module-level breaker boards (e.g. moe EXPERT_BREAKERS) too
    RECORDER.clear()  # one test's spans must not satisfy another's assertions
    RECORDER.slow_threshold = float(os.environ.get("HIVEMIND_SLOW_SPAN_S", "10.0"))
    LEDGER.clear()  # one test's round records must not satisfy another's assertions
    SERVING_LEDGER.clear()  # serving records + expert scorecards likewise
    SCORECARDS.clear()
    reset_device_telemetry()  # compile counts/memory trend/timeline + disarm
    telemetry_watchdog.shutdown_all()  # watchdog threads re-arm with the next loop owner
    Ed25519PrivateKey.reset_process_wide()
    gc.collect()

    # thread sanitizer (ISSUE 16 satellite): a test must not strand non-daemon
    # threads — they outlive the suite and wedge interpreter shutdown. The
    # shared hmtpu-* executors are process-lifetime infrastructure by design.
    if request.node.get_closest_marker("allow_thread_leaks") is None:

        def _stragglers():
            return [
                thread
                for thread in threading.enumerate()
                if thread.ident not in thread_baseline
                and thread.is_alive()
                and not thread.daemon
                and not thread.name.startswith("hmtpu-")
            ]

        deadline = time.monotonic() + 3.0
        leaked_threads = _stragglers()
        while leaked_threads and time.monotonic() < deadline:
            time.sleep(0.05)  # teardown joins may still be in flight
            leaked_threads = _stragglers()
        if leaked_threads:
            pytest.fail(
                "thread sanitizer: non-daemon thread(s) leaked by this test: "
                f"{sorted(thread.name for thread in leaked_threads)} — join them in "
                "teardown (or mark the test @pytest.mark.allow_thread_leaks)"
            )


@pytest.fixture
def event_loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()
