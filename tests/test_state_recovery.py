"""ISSUE 7: crash-safe state recovery — verified/resumable/striped state sync,
stale-donor rejection, shutdown retraction, and the local checkpoint store
(scope: reference averager.py:628-651 load_state_from_peers, hardened)."""

import asyncio
import time

import numpy as np
import pytest

from hivemind_tpu.averaging import DecentralizedAverager
from hivemind_tpu.averaging.state_sync import (
    _STATE_SYNC_DIGEST_FAILURES,
    _STATE_SYNC_FAILOVERS,
    _STATE_SYNC_STALE_DONORS,
    DigestMismatch,
    ManifestMismatch,
    StaleDonor,
    StateAssembly,
    StateUnavailable,
    _list_donor_candidates,
    _split_for_striping,
    _stream_from_donor,
    _try_striped_fetch,
    build_state_manifest,
)
from hivemind_tpu.compression import serialize_tensor, split_tensor_for_streaming
from hivemind_tpu.compression.base import NoCompression
from hivemind_tpu.optim.recovery import LocalCheckpointStore
from hivemind_tpu.proto import averaging_pb2, runtime_pb2
from hivemind_tpu.resilience import CHAOS, Deadline

from swarm_utils import launch_dht_swarm, shutdown_all


# ------------------------------------------------------------------ helpers


def _state_tensors(seed: int, n: int = 2):
    rng = np.random.RandomState(seed)
    return [rng.randn(123).astype(np.float32), rng.randn(3, 5).astype(np.float32)][:n]


def _serialized_state(tensors):
    return [serialize_tensor(t, NoCompression()) for t in tensors]


def _manifest_for(serialized, epoch=0, schema_hash="test-schema"):
    return build_state_manifest(serialized, schema_hash=schema_hash, epoch=epoch)


class _ScriptedStub:
    """An in-memory donor: serves a scripted manifest + chunk stream, records the
    ``have_tensors`` of every request, optionally dies after N chunk messages."""

    def __init__(self, serialized, manifest, *, fail_after_chunks=None, chunk_bytes=200):
        self.serialized = serialized
        self.manifest = manifest
        self.fail_after_chunks = fail_after_chunks
        self.chunk_bytes = chunk_bytes
        self.requests = []

    def rpc_download_state(self, request, timeout=None):
        self.requests.append(request)

        async def _gen():
            yield averaging_pb2.DownloadData(manifest=self.manifest)
            if request.manifest_only:
                return
            have = set(request.have_tensors)
            sent = 0
            for index, tensor in enumerate(self.serialized):
                if index in have:
                    continue
                for chunk in split_tensor_for_streaming(tensor, self.chunk_bytes):
                    if self.fail_after_chunks is not None and sent >= self.fail_after_chunks:
                        raise ConnectionError("scripted donor died mid-stream")
                    sent += 1
                    yield averaging_pb2.DownloadData(tensor_part=chunk, tensor_index=index)

        return _gen()


# ------------------------------------------------------------------ assembly units


def test_assembly_verifies_tensors_and_rejects_corruption():
    tensors = _state_tensors(0)
    serialized = _serialized_state(tensors)
    manifest = _manifest_for(serialized)
    assembly = StateAssembly()
    assembly.pin_manifest(manifest, "donor")

    # a flipped byte is caught at the tensor boundary, nothing is adopted
    corrupt = runtime_pb2.Tensor()
    corrupt.CopyFrom(serialized[0])
    payload = bytearray(corrupt.buffer)
    payload[7] ^= 0xFF
    corrupt.buffer = bytes(payload)
    with pytest.raises(DigestMismatch):
        assembly.feed(0, corrupt)
    assert 0 not in assembly.verified and assembly.digest_failures == 1

    # the same index recovers with the genuine bytes (failover donor)
    assembly.feed(0, serialized[0])
    assembly.feed(1, serialized[1])
    assert assembly.complete()
    result = assembly.result(["donor"])
    assert result.verified
    for got, want in zip(result.tensors, tensors):
        assert np.array_equal(got, want.astype(np.float32))


def test_assembly_rejects_stale_epoch_schema_and_unavailable():
    serialized = _serialized_state(_state_tensors(0))
    stale_before = _STATE_SYNC_STALE_DONORS.value()

    assembly = StateAssembly(min_epoch=5)
    with pytest.raises(StaleDonor):
        assembly.pin_manifest(_manifest_for(serialized, epoch=3), "old-donor")
    assert _STATE_SYNC_STALE_DONORS.value() == stale_before + 1
    assembly.pin_manifest(_manifest_for(serialized, epoch=5), "fresh-donor")  # boundary OK

    with pytest.raises(ManifestMismatch):
        StateAssembly(schema_hash="ours").pin_manifest(
            _manifest_for(serialized, schema_hash="theirs"), "donor"
        )
    with pytest.raises(ManifestMismatch):
        StateAssembly(expected_tensors=5).pin_manifest(_manifest_for(serialized), "donor")
    with pytest.raises(StateUnavailable):
        StateAssembly().pin_manifest(
            averaging_pb2.StateManifest(state_unavailable=True), "donor"
        )


def test_assembly_repin_on_divergent_failover_but_not_for_stripes():
    serialized_a = _serialized_state(_state_tensors(0))
    serialized_b = _serialized_state(_state_tensors(1))
    assembly = StateAssembly()
    assembly.pin_manifest(_manifest_for(serialized_a), "a")
    assembly.feed(0, serialized_a[0])
    assert list(assembly.verified) == [0]

    # a striping donor must match bit-for-bit
    with pytest.raises(ManifestMismatch):
        assembly.pin_manifest(_manifest_for(serialized_b), "b", allow_repin=False)
    assert list(assembly.verified) == [0]  # untouched

    # a failover donor with a different VALID state resets the assembly
    assembly.pin_manifest(_manifest_for(serialized_b), "b")
    assert not assembly.verified
    assembly.feed(0, serialized_b[0])
    assembly.feed(1, serialized_b[1])
    assert assembly.complete()


def test_stream_resume_continues_from_last_verified_tensor():
    """The headline resume guarantee: after donor A dies mid-stream, the request
    to donor B names exactly the already-verified tensors so only the missing
    ones travel again — and the final state is bitwise identical."""
    tensors = _state_tensors(3)
    serialized = _serialized_state(tensors)
    manifest = _manifest_for(serialized)
    # tensor 0 is 492 bytes → 3 chunks at 200 B; die right after it completes
    donor_a = _ScriptedStub(serialized, manifest, fail_after_chunks=3)
    donor_b = _ScriptedStub(serialized, manifest)
    assembly = StateAssembly()

    async def _run():
        with pytest.raises(ConnectionError):
            await _stream_from_donor(
                donor_a, assembly, "donor-a", want=None, deadline=Deadline(10)
            )
        assert list(assembly.verified) == [0], "tensor 0 must survive the donor's death"
        await _stream_from_donor(donor_b, assembly, "donor-b", want=None, deadline=Deadline(10))

    asyncio.run(_run())
    assert list(donor_b.requests[0].have_tensors) == [0], (
        "the failover request must resume after the last verified tensor"
    )
    assert assembly.complete()
    for got, want in zip(assembly.result(["a", "b"]).tensors, tensors):
        assert np.array_equal(got, want.astype(np.float32))


def test_divergent_failover_donor_completes_without_livelock(monkeypatch):
    """Regression: the failover request's have_tensors is computed against the
    OLD manifest; when the new donor's (valid, divergent) manifest re-pins the
    assembly, the donor was told to skip tensors the repin just discarded. One
    immediate same-donor retry with the fresh have-set must complete the
    download — not fail over in circles against an actively-training donor."""
    import hivemind_tpu.averaging.state_sync as state_sync_module
    from hivemind_tpu.averaging.state_sync import download_state_verified

    tensors_a, tensors_b = _state_tensors(0), _state_tensors(1)
    serialized_a, serialized_b = _serialized_state(tensors_a), _serialized_state(tensors_b)
    # donor A completes tensor 0 (3 chunks at 200 B), then dies mid-stream
    stubs = {
        "a": _ScriptedStub(serialized_a, _manifest_for(serialized_a), fail_after_chunks=3),
        "b": _ScriptedStub(serialized_b, _manifest_for(serialized_b)),
    }

    async def _fake_candidates(dht, prefix, exclude_peer_id):
        return ["a", "b"]

    monkeypatch.setattr(state_sync_module, "_list_donor_candidates", _fake_candidates)

    result = asyncio.run(
        download_state_verified(
            None, None, "livelock", lambda p2p, donor, namespace: stubs[str(donor)],
            timeout=10,
        )
    )
    assert result is not None and result.verified
    for got, want in zip(result.tensors, tensors_b):
        assert np.array_equal(got, want.astype(np.float32))
    # donor B saw the inverted request first (skip tensor 0, verified under A's
    # manifest); tensor 1 still landed and re-verified under B's re-pinned
    # manifest, so the immediate same-donor retry re-requests ONLY tensor 0
    payload_requests = [r for r in stubs["b"].requests if not r.manifest_only]
    assert [list(r.have_tensors) for r in payload_requests] == [[0], [1]]


def _big_state(n_tensors=8, floats_each=1 << 18):
    rng = np.random.RandomState(42)
    return [rng.randn(floats_each).astype(np.float32) for _ in range(n_tensors)]


def test_striped_fetch_downloads_disjoint_halves_concurrently():
    """Two donors with bit-identical manifests each carry roughly half the
    missing bytes; the merged assembly is complete and bitwise correct."""
    tensors = _big_state()  # 8 x 1 MiB: far past MIN_STRIPE_BYTES
    serialized = _serialized_state(tensors)
    manifest = _manifest_for(serialized)
    stubs = {
        "a": _ScriptedStub(serialized, manifest, chunk_bytes=1 << 20),
        "b": _ScriptedStub(serialized, manifest, chunk_bytes=1 << 20),
    }
    assembly = StateAssembly()
    assembly.pin_manifest(manifest, "a")

    async def _run():
        return await _try_striped_fetch(
            assembly, "a", ["b"],
            get_stub=lambda p2p, donor, namespace: stubs[str(donor)],
            p2p=None, prefix="striped", deadline=Deadline(30),
            max_stripes=2, used_donors=[],
        )

    assert asyncio.run(_run()) is True
    assert assembly.complete()
    for got, want in zip(assembly.result(["a", "b"]).tensors, tensors):
        assert np.array_equal(got, want)
    # the LAST request each stub saw is the payload fetch (b's first was the
    # manifest probe); their have-sets must partition the tensors disjointly
    want_a = set(range(len(tensors))) - set(stubs["a"].requests[-1].have_tensors)
    want_b = set(range(len(tensors))) - set(stubs["b"].requests[-1].have_tensors)
    assert want_a and want_b and not (want_a & want_b)
    assert want_a | want_b == set(range(len(tensors)))


def test_striped_fetch_survives_one_stripe_dying():
    """A stripe donor dying mid-transfer loses only its own share: the other
    stripe's tensors stay verified and the failover loop finishes the rest."""
    tensors = _big_state()
    serialized = _serialized_state(tensors)
    manifest = _manifest_for(serialized)
    dying = _ScriptedStub(serialized, manifest, chunk_bytes=1 << 20, fail_after_chunks=1)
    healthy = _ScriptedStub(serialized, manifest, chunk_bytes=1 << 20)
    stubs = {"a": healthy, "b": dying}
    assembly = StateAssembly()
    assembly.pin_manifest(manifest, "a")

    async def _run():
        return await _try_striped_fetch(
            assembly, "a", ["b"],
            get_stub=lambda p2p, donor, namespace: stubs[str(donor)],
            p2p=None, prefix="striped", deadline=Deadline(30),
            max_stripes=2, used_donors=[],
        )

    assert asyncio.run(_run()) is True
    healthy_share = set(range(len(tensors))) - set(healthy.requests[-1].have_tensors)
    assert healthy_share <= set(assembly.verified), "the surviving stripe must be intact"
    assert not assembly.complete(), "the dead stripe's share is still missing"
    for index in assembly.verified:
        assert np.array_equal(assembly.verified[index], tensors[index])


def test_split_for_striping_is_balanced_and_complete():
    rng = np.random.RandomState(0)
    tensors = [rng.randn(n).astype(np.float32) for n in (1000, 10, 500, 300, 7, 900)]
    serialized = _serialized_state(tensors)
    assembly = StateAssembly()
    assembly.pin_manifest(_manifest_for(serialized), "donor")
    stripes = _split_for_striping(assembly, 2)
    flat = sorted(index for stripe in stripes for index in stripe)
    assert flat == list(range(len(tensors))), "every tensor assigned exactly once"
    loads = [
        sum(int(assembly.manifest.tensors[i].num_bytes) for i in stripe) for stripe in stripes
    ]
    assert max(loads) <= 2 * min(loads), f"stripes badly unbalanced: {loads}"


# ------------------------------------------------------------------ checkpoints


def test_checkpoint_store_roundtrip_retention_and_digest(tmp_path):
    store = LocalCheckpointStore(tmp_path, keep_last=2)
    states = {
        epoch: {
            "epoch": epoch,
            "tensors": [t * epoch for t in _state_tensors(0)],
            "opt_counts": [epoch],
        }
        for epoch in (1, 2, 3)
    }
    for epoch in (1, 2, 3):
        store.save(states[epoch])
    assert len(store.checkpoints()) == 2, "retention must prune beyond keep_last"
    loaded = store.load_latest()
    assert loaded["epoch"] == 3 and loaded["opt_counts"] == [3]
    for got, want in zip(loaded["tensors"], states[3]["tensors"]):
        assert np.array_equal(got, want)


def test_checkpoint_kill_during_save_leaves_previous_loadable(tmp_path):
    """kill -9 atomicity: a crash at ANY point of a save leaves the previous
    checkpoint adoptable — a torn temp file is invisible, and a torn final file
    is rejected by its digest."""
    store = LocalCheckpointStore(tmp_path, keep_last=3)
    good = {"epoch": 7, "tensors": _state_tensors(1), "opt_counts": []}
    store.save(good)

    # crash BEFORE the rename: only a temp file exists for epoch 8 (aged so the
    # sweep treats it as a dead process's leftovers, not a live writer's file)
    import os

    torn_tmp = tmp_path / ".state-save-killed9.tmp"
    torn_tmp.write_bytes(b"half a checkpoint")
    old = 1e9
    os.utime(torn_tmp, (old, old))
    # crash that somehow tore the published bytes: valid name, wrong digest
    fake = tmp_path / f"state-e{8:012d}-{'ab' * 16}.ckpt.npz"
    fake.write_bytes(b"torn npz bytes")

    loaded = store.load_latest()
    assert loaded is not None and loaded["epoch"] == 7
    for got, want in zip(loaded["tensors"], good["tensors"]):
        assert np.array_equal(got, np.asarray(want))
    store.prune()
    assert not torn_tmp.exists(), "interrupted temp files are swept"


# ------------------------------------------------------------------ real-swarm paths


def _make_averagers(dhts, prefix="recovtest", seeds=None, **kwargs):
    averagers = []
    for index, dht in enumerate(dhts):
        tensors = _state_tensors(seeds[index] if seeds else index)
        averagers.append(
            DecentralizedAverager(
                tensors, dht, prefix=prefix, start=True,
                min_matchmaking_time=1.0, request_timeout=1.0,
                declare_state_period=0.5, **kwargs,
            )
        )
    return averagers


def _download_rich(averager, timeout=25, min_epoch=None):
    future = averager._runner.run_coroutine(
        averager._load_state_from_peers_async(timeout, min_epoch=min_epoch), return_future=True
    )
    return future.result(timeout + 10)


def test_corrupt_donor_fails_over_without_adopting_bad_state():
    """A donor whose every payload is corrupted in flight must never poison the
    receiver: digests reject it, the download fails over, and the adopted state
    is bitwise the clean donor's snapshot."""
    dhts = launch_dht_swarm(3)
    averagers = _make_averagers(dhts)
    corrupt_donor, clean_donor, receiver = averagers
    corrupt_donor.state_sharing_priority = 10.0  # tried first
    clean_donor.state_sharing_priority = 1.0
    receiver.allow_state_sharing = False
    digest_before = _STATE_SYNC_DIGEST_FAILURES.value(site="download")
    failover_before = _STATE_SYNC_FAILOVERS.value()
    try:
        time.sleep(1.5)  # let declarations propagate
        CHAOS.add_rule(
            "state.download.send", "corrupt_payload", scope=str(corrupt_donor.peer_id)
        )
        result = _download_rich(receiver, timeout=25)
        assert result is not None and result.verified
        with clean_donor.get_tensors() as donor_tensors:
            for got, want in zip(result.tensors, donor_tensors):
                assert np.array_equal(got, want.astype(np.float32)), (
                    "adopted state must be bitwise the clean donor's snapshot"
                )
        with corrupt_donor.get_tensors() as bad_tensors:
            assert not all(
                np.array_equal(got, want.astype(np.float32))
                for got, want in zip(result.tensors, bad_tensors)
            ), "the corrupt donor's state must not have been adopted"
        assert _STATE_SYNC_DIGEST_FAILURES.value(site="download") > digest_before
        assert _STATE_SYNC_FAILOVERS.value() > failover_before
    finally:
        CHAOS.clear()
        shutdown_all(averagers, dhts)


def test_truncated_stream_fails_over_to_next_donor():
    """A donor dying mid-stream (stream ends early / errors) must not yield a
    truncated adoption: the receiver fails over and lands on complete state."""
    dhts = launch_dht_swarm(3)
    averagers = _make_averagers(dhts)
    dying_donor, healthy_donor, receiver = averagers
    dying_donor.state_sharing_priority = 10.0
    healthy_donor.state_sharing_priority = 1.0
    receiver.allow_state_sharing = False
    try:
        time.sleep(1.5)
        # first chunk passes, everything after is eaten: a classic mid-stream death
        CHAOS.add_rule(
            "state.download.send", "drop", after=1, scope=str(dying_donor.peer_id)
        )
        result = _download_rich(receiver, timeout=25)
        assert result is not None and result.verified
        assert len(result.tensors) == 2, "a truncated stream must never be adopted"
        with healthy_donor.get_tensors() as donor_tensors:
            for got, want in zip(result.tensors, donor_tensors):
                assert np.array_equal(got, want.astype(np.float32))
    finally:
        CHAOS.clear()
        shutdown_all(averagers, dhts)


class _EpochAverager(DecentralizedAverager):
    """Test donor that advertises a fixed epoch in its state metadata."""

    def __init__(self, *args, epoch=0, **kwargs):
        self._test_epoch = epoch
        super().__init__(*args, **kwargs)

    async def _get_current_state(self):
        return {"epoch": self._test_epoch}, self._snapshot_tensors()


def test_stale_epoch_donor_is_rejected():
    """A donor whose manifest epoch is behind the required minimum (the tracker's
    global epoch at the call site) is rejected at the manifest — the fresh donor
    wins even when the stale one has better priority."""
    dhts = launch_dht_swarm(3)
    shared = _state_tensors(0)
    stale = _EpochAverager(
        [t.copy() for t in shared], dhts[0], prefix="staletest", start=True, epoch=3,
        min_matchmaking_time=1.0, request_timeout=1.0, declare_state_period=0.5,
    )
    fresh_tensors = _state_tensors(9)
    fresh = _EpochAverager(
        fresh_tensors, dhts[1], prefix="staletest", start=True, epoch=7,
        min_matchmaking_time=1.0, request_timeout=1.0, declare_state_period=0.5,
    )
    receiver = _EpochAverager(
        [t.copy() for t in shared], dhts[2], prefix="staletest", start=True, epoch=0,
        min_matchmaking_time=1.0, request_timeout=1.0, declare_state_period=0.5,
        allow_state_sharing=False,
    )
    stale.state_sharing_priority = 10.0
    fresh.state_sharing_priority = 1.0
    stale_before = _STATE_SYNC_STALE_DONORS.value()
    try:
        time.sleep(1.5)
        result = _download_rich(receiver, timeout=25, min_epoch=5)
        assert result is not None and result.verified
        assert result.epoch == 7, "only the fresh donor may be adopted"
        for got, want in zip(result.tensors, fresh_tensors):
            assert np.array_equal(got, want.astype(np.float32))
        assert _STATE_SYNC_STALE_DONORS.value() > stale_before
    finally:
        shutdown_all([stale, fresh, receiver], dhts)


def test_sharing_disabled_is_explicit_not_truncation():
    """A donor that declared state but turned sharing off answers with an explicit
    state_unavailable manifest; the download returns None instead of adopting an
    empty stream as state."""
    dhts = launch_dht_swarm(2)
    averagers = _make_averagers(dhts, seeds=[0, 1])
    donor, receiver = averagers
    try:
        time.sleep(1.5)  # declared while sharing was on
        donor._allow_state_sharing = False  # raw flag: the declaration stays live
        result = _download_rich(receiver, timeout=6)
        assert result is None
    finally:
        shutdown_all(averagers, dhts)


def test_shutdown_retracts_state_declaration():
    """ISSUE 7 satellite: a cleanly-departed donor must not cost joiners a dial —
    its ``all_averagers`` record is tombstoned at shutdown."""
    dhts = launch_dht_swarm(2)
    averagers = _make_averagers(dhts, prefix="retracttest")
    retiring, survivor = averagers
    try:
        time.sleep(1.5)

        async def _candidates(_dht, _node):
            return await _list_donor_candidates(_dht, "retracttest", None)

        before = dhts[1].run_coroutine(_candidates)
        assert retiring.peer_id in before, "donor must be declared before shutdown"
        retiring.shutdown()
        time.sleep(0.5)  # let the tombstone replicate
        after = dhts[1].run_coroutine(_candidates)
        assert retiring.peer_id not in after, "shutdown must retract the declaration"
        assert survivor.peer_id in after, "the live donor must remain declared"
    finally:
        survivor.shutdown()
        for dht in dhts:
            dht.shutdown()


# ------------------------------------------------------------------ optimizer integration


def test_optimizer_checkpoint_restore_cycle(tmp_path):
    """The restore order's local leg: a solo trainer checkpoints on its epoch
    cadence; a restarted process adopts the newest checkpoint bitwise — no swarm
    download needed."""
    import optax

    import jax.numpy as jnp

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import Optimizer

    dht = DHT(start=True)
    try:
        def make_opt(d):
            return Optimizer(
                dht=d, run_id="ckpt_cycle", target_batch_size=32,
                params={"w": jnp.zeros(8, jnp.float32)}, optimizer=optax.sgd(0.1),
                batch_size_per_step=32, matchmaking_time=0.5, averaging_timeout=10,
                checkpoint_dir=tmp_path, checkpoint_every=1,
                tracker_opts=dict(min_refresh_period=0.2, default_refresh_period=0.3),
            )

        opt = make_opt(dht)
        rng = np.random.RandomState(0)
        grads_tree = {"w": jnp.asarray(rng.randn(8).astype(np.float32))}
        for _ in range(3):  # solo swarm: every full batch advances the epoch
            opt.step(grads_tree)
            time.sleep(0.1)
        saved_epoch = opt.local_epoch
        saved_state = opt.state_dict()
        assert saved_epoch >= 1, "the solo trainer must have advanced epochs"
        assert store_nonempty(tmp_path)
        opt.shutdown()

        # "reboot": same checkpoint dir, fresh everything else
        dht2 = DHT(start=True)
        try:
            restarted = make_opt(dht2)
            assert restarted.local_epoch == saved_epoch
            for got, want in zip(
                restarted.state_averager._host_state_tensors(), saved_state["tensors"]
            ):
                assert np.array_equal(got, np.asarray(want, dtype=np.float32))
            restarted.shutdown()
        finally:
            dht2.shutdown()
    finally:
        dht.shutdown()


def store_nonempty(path) -> bool:
    return bool(LocalCheckpointStore(path).checkpoints())


def test_epoch_adopted_without_state_is_loud_and_counted(tmp_path):
    """ISSUE 7 satellite: when the download fails, fast-forwarding the epoch
    number is an emergency, not business as usual — counted and logged at ERROR."""
    import optax

    import jax.numpy as jnp

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import Optimizer
    from hivemind_tpu.optim.optimizer import _EPOCH_ADOPTED_WITHOUT_STATE

    dht = DHT(start=True)
    opt = Optimizer(
        dht=dht, run_id="adopt_test", target_batch_size=64,
        params={"w": jnp.zeros(4, jnp.float32)}, optimizer=optax.sgd(0.1),
        batch_size_per_step=16, matchmaking_time=0.5,
        tracker_opts=dict(min_refresh_period=0.2, default_refresh_period=0.3),
    )
    try:
        opt.state_averager.load_full_state_from_peers = lambda **kwargs: False
        opt.tracker.global_progress.global_epoch = 5
        before = _EPOCH_ADOPTED_WITHOUT_STATE.value()
        opt._catch_up_with_swarm()
        assert opt.local_epoch == 5, "the epoch number is still adopted (anti-livelock)"
        assert _EPOCH_ADOPTED_WITHOUT_STATE.value() == before + 1
    finally:
        opt.shutdown()
        dht.shutdown()
