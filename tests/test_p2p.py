"""Transport tests — scope mirrors reference tests/test_p2p_daemon.py +
test_p2p_servicer.py: lifecycle, identity, unary/stream handlers, errors,
cancellation, servicer reflection."""

import asyncio
from typing import AsyncIterator

import pytest

from hivemind_tpu.p2p import (
    P2P,
    Multiaddr,
    P2PContext,
    P2PHandlerError,
    PeerID,
    PeerNotFoundError,
    ServicerBase,
)
from hivemind_tpu.p2p.peer_id import base58_decode, base58_encode
from hivemind_tpu.proto import test_pb2


def test_base58_roundtrip():
    for data in [b"", b"\x00\x00abc", b"hello world", bytes(range(256))]:
        assert base58_decode(base58_encode(data)) == data
    with pytest.raises(ValueError):
        base58_decode("0OIl")  # excluded characters


def test_peer_id_and_multiaddr():
    from hivemind_tpu.utils.crypto import Ed25519PrivateKey

    key = Ed25519PrivateKey()
    pid = PeerID.from_private_key(key)
    assert PeerID.from_base58(pid.to_base58()) == pid
    maddr = Multiaddr.parse(f"/ip4/127.0.0.1/tcp/1234/p2p/{pid.to_base58()}")
    assert maddr.host == "127.0.0.1" and maddr.port == 1234 and maddr.peer_id == pid
    assert Multiaddr.parse(str(maddr)) == maddr
    with pytest.raises(ValueError):
        Multiaddr.parse("/udp/53")

    # reference vendored-multiaddr codec extras: unix + onion3 round-trip
    unix = Multiaddr.parse("/unix/tmp/sockets/p2p.sock")
    assert unix.host_proto == "unix" and unix.host == "/tmp/sockets/p2p.sock"
    assert Multiaddr.parse(str(unix)) == unix
    # ...including a pinned peer identity (hole-punch serialization reparses str)
    unix_pid = unix.with_peer_id(pid)
    assert Multiaddr.parse(str(unix_pid)) == unix_pid
    onion_host = "a" * 56
    onion = Multiaddr.parse(f"/onion3/{onion_host}:9443")
    assert onion.host_proto == "onion3" and onion.host == onion_host and onion.port == 9443
    assert Multiaddr.parse(str(onion)) == onion
    # protocols are part of identity: same host+port, different proto, distinct
    assert onion != Multiaddr.parse(f"/dns/{onion_host}/tcp/9443")
    # a path whose last segments merely LOOK base58 stays a path (only a real
    # sha2-256 multihash identity is stripped as /p2p/<id>)
    plain_path = Multiaddr.parse("/unix/var/run/p2p/sock")
    assert plain_path.host == "/var/run/p2p/sock" and plain_path.peer_id is None
    with pytest.raises(ValueError):
        Multiaddr.parse("/onion3/tooshort:1")


async def test_p2p_lifecycle_and_identity(tmp_path):
    ident = str(tmp_path / "id.key")
    p2p = await P2P.create(identity_path=ident)
    peer_id = p2p.peer_id
    maddrs = p2p.get_visible_maddrs()
    assert len(maddrs) == 1 and maddrs[0].peer_id == peer_id
    await p2p.shutdown()
    # identity persists across restarts
    p2p2 = await P2P.create(identity_path=ident)
    assert p2p2.peer_id == peer_id
    await p2p2.shutdown()


async def test_unary_handler_and_errors():
    server = await P2P.create()
    client = await P2P.create()

    async def square(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
        assert context.remote_id == client.peer_id
        return test_pb2.TestResponse(number=request.number**2)

    async def fail(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
        raise ValueError("deliberate failure")

    await server.add_protobuf_handler("square", square, test_pb2.TestRequest)
    await server.add_protobuf_handler("fail", fail, test_pb2.TestRequest)

    await client.connect(server.get_visible_maddrs()[0])
    response = await client.call_protobuf_handler(
        server.peer_id, "square", test_pb2.TestRequest(number=12), test_pb2.TestResponse
    )
    assert response.number == 144

    with pytest.raises(P2PHandlerError, match="deliberate failure"):
        await client.call_protobuf_handler(
            server.peer_id, "fail", test_pb2.TestRequest(number=1), test_pb2.TestResponse
        )
    with pytest.raises(P2PHandlerError, match="unknown handler"):
        await client.call_protobuf_handler(
            server.peer_id, "nonexistent", test_pb2.TestRequest(number=1), test_pb2.TestResponse
        )

    await client.shutdown()
    await server.shutdown()


async def test_streaming_handler_both_directions():
    server = await P2P.create()
    client = await P2P.create()

    async def partial_sums(
        requests: AsyncIterator[test_pb2.TestRequest], context: P2PContext
    ) -> AsyncIterator[test_pb2.TestResponse]:
        total = 0
        async for request in requests:
            total += request.number
            yield test_pb2.TestResponse(number=total)

    await server.add_protobuf_handler(
        "partial_sums", partial_sums, test_pb2.TestRequest, stream_input=True, stream_output=True
    )
    await client.connect(server.get_visible_maddrs()[0])

    async def gen():
        for i in [1, 2, 3, 4]:
            yield test_pb2.TestRequest(number=i)

    sums = [
        r.number
        async for r in client.iterate_protobuf_handler(
            server.peer_id, "partial_sums", gen(), test_pb2.TestResponse
        )
    ]
    assert sums == [1, 3, 6, 10]
    await client.shutdown()
    await server.shutdown()


async def test_dial_failures():
    client = await P2P.create()
    with pytest.raises((OSError, asyncio.TimeoutError, ConnectionError)):
        await client.connect("/ip4/127.0.0.1/tcp/1")  # nothing listening
    with pytest.raises(PeerNotFoundError):
        from hivemind_tpu.utils.crypto import Ed25519PrivateKey

        unknown = PeerID.from_private_key(Ed25519PrivateKey())
        await client.call_protobuf_handler(unknown, "x", b"", None)
    await client.shutdown()


async def test_wrong_expected_peer_rejected():
    from hivemind_tpu.p2p.crypto_channel import HandshakeError
    from hivemind_tpu.utils.crypto import Ed25519PrivateKey

    server = await P2P.create()
    client = await P2P.create()
    impostor = PeerID.from_private_key(Ed25519PrivateKey())
    bad_maddr = Multiaddr("127.0.0.1", server.listen_port, impostor)
    with pytest.raises(HandshakeError, match="dialed"):
        await client.connect(bad_maddr)
    await client.shutdown()
    await server.shutdown()


async def test_server_streaming_cancellation():
    server = await P2P.create()
    client = await P2P.create()
    served = asyncio.Event()
    cancelled = asyncio.Event()

    async def infinite(request: test_pb2.TestRequest, context: P2PContext) -> AsyncIterator[test_pb2.TestResponse]:
        try:
            n = 0
            while True:
                yield test_pb2.TestResponse(number=n)
                n += 1
                served.set()
                await asyncio.sleep(0.001)
        except (asyncio.CancelledError, ConnectionError):
            cancelled.set()
            raise

    await server.add_protobuf_handler("infinite", infinite, test_pb2.TestRequest, stream_output=True)
    await client.connect(server.get_visible_maddrs()[0])

    iterator = client.iterate_protobuf_handler(
        server.peer_id, "infinite", test_pb2.TestRequest(number=0), test_pb2.TestResponse
    )
    received = 0
    async for _ in iterator:
        received += 1
        if received >= 3:
            break  # closes the generator → resets the stream
    assert served.is_set()
    await client.shutdown()
    await server.shutdown()


async def test_large_messages():
    server = await P2P.create()
    client = await P2P.create()

    async def echo_len(request: bytes, context: P2PContext) -> bytes:
        return len(request).to_bytes(8, "big")

    await server.add_protobuf_handler("echo_len", echo_len, bytes)
    await client.connect(server.get_visible_maddrs()[0])
    payload = b"x" * (3 * 1024 * 1024)  # 3 MiB through the AEAD + mux path
    result = await client.call_protobuf_handler(server.peer_id, "echo_len", payload, bytes)
    assert int.from_bytes(result, "big") == len(payload)
    await client.shutdown()
    await server.shutdown()


class MathServicer(ServicerBase):
    async def rpc_square(self, request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
        return test_pb2.TestResponse(number=request.number**2)

    async def rpc_count(self, request: test_pb2.TestRequest, context: P2PContext) -> AsyncIterator[test_pb2.TestResponse]:
        for i in range(request.number):
            yield test_pb2.TestResponse(number=i)

    async def rpc_sum(self, requests: AsyncIterator[test_pb2.TestRequest], context: P2PContext) -> test_pb2.TestResponse:
        total = 0
        async for request in requests:
            total += request.number
        return test_pb2.TestResponse(number=total)

    async def rpc_slow_count(self, request: test_pb2.TestRequest, context: P2PContext) -> AsyncIterator[test_pb2.TestResponse]:
        for i in range(request.number):
            await asyncio.sleep(5)
            yield test_pb2.TestResponse(number=i)


async def test_servicer_reflection():
    specs = {s.method_name: s for s in MathServicer._collect_rpc_specs()}
    assert not specs["rpc_square"].stream_input and not specs["rpc_square"].stream_output
    assert not specs["rpc_count"].stream_input and specs["rpc_count"].stream_output
    assert specs["rpc_sum"].stream_input and not specs["rpc_sum"].stream_output
    assert specs["rpc_slow_count"].stream_output

    server = await P2P.create()
    client = await P2P.create()
    servicer = MathServicer()
    await servicer.add_p2p_handlers(server)
    await client.connect(server.get_visible_maddrs()[0])

    stub = MathServicer.get_stub(client, server.peer_id)
    assert (await stub.rpc_square(test_pb2.TestRequest(number=9))).number == 81
    counted = [r.number async for r in stub.rpc_count(test_pb2.TestRequest(number=4))]
    assert counted == [0, 1, 2, 3]

    async def gen():
        for i in range(5):
            yield test_pb2.TestRequest(number=i)

    assert (await stub.rpc_sum(gen())).number == 10

    with pytest.raises(asyncio.TimeoutError):
        async for _ in stub.rpc_slow_count(test_pb2.TestRequest(number=1), timeout=0.1):
            pass

    await client.shutdown()
    await server.shutdown()


async def test_servicer_namespaces():
    server = await P2P.create()
    client = await P2P.create()
    servicer_a, servicer_b = MathServicer(), MathServicer()
    await servicer_a.add_p2p_handlers(server, namespace="a")
    await servicer_b.add_p2p_handlers(server, namespace="b")
    await client.connect(server.get_visible_maddrs()[0])
    stub_a = MathServicer.get_stub(client, server.peer_id, namespace="a")
    assert (await stub_a.rpc_square(test_pb2.TestRequest(number=3))).number == 9
    stub_missing = MathServicer.get_stub(client, server.peer_id, namespace="missing")
    with pytest.raises(P2PHandlerError):
        await stub_missing.rpc_square(test_pb2.TestRequest(number=3))
    await client.shutdown()
    await server.shutdown()


async def test_mux_rejects_invalid_open_frames():
    """OPEN frames with local-parity or already-used stream ids must be RESET, not
    silently replace a live stream (ADVICE r1: stream hijack via id collision)."""
    from hivemind_tpu.p2p.mux import Flags

    server = await P2P.create()
    client = await P2P.create()
    try:
        async def echo(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
            return test_pb2.TestResponse(number=request.number)

        await server.add_protobuf_handler("echo", echo, test_pb2.TestRequest)
        await client.connect(server.get_visible_maddrs()[0])
        response = await client.call_protobuf_handler(
            server.peer_id, "echo", test_pb2.TestRequest(number=7), test_pb2.TestResponse
        )
        assert response.number == 7

        conn = client._connections[server.peer_id]
        # client is the initiator: its local ids are odd. A remote OPEN with an odd
        # id (wrong parity) must be rejected...
        local_parity_id = conn._next_stream_id  # odd, unused
        await conn._dispatch(local_parity_id, Flags.OPEN, b"echo")
        assert local_parity_id not in conn._streams
        # ...and so must an OPEN duplicating an id that is already live
        stream = await conn.open_stream("echo")
        before = conn._streams[stream.stream_id]
        await conn._dispatch(stream.stream_id, Flags.OPEN, b"echo")
        assert conn._streams[stream.stream_id] is before
        # valid remote-parity OPEN still works
        await conn._dispatch(1000, Flags.OPEN, b"echo")
        assert 1000 in conn._streams
    finally:
        await client.shutdown()
        await server.shutdown()


async def test_many_concurrent_streams_one_connection():
    """Stress the mux: many interleaved unary + streaming calls share ONE encrypted
    connection; every response routes to the right stream (race-detection parity:
    the reference exercises concurrency with real parallel calls)."""
    server = await P2P.create()
    client = await P2P.create()
    try:
        async def square(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
            await asyncio.sleep(0.001 * (request.number % 7))  # shuffle completion order
            return test_pb2.TestResponse(number=request.number ** 2)

        async def countdown(request: test_pb2.TestRequest, context: P2PContext):
            for value in range(request.number, 0, -1):
                yield test_pb2.TestResponse(number=value)

        await server.add_protobuf_handler("square", square, test_pb2.TestRequest)
        await server.add_protobuf_handler("countdown", countdown, test_pb2.TestRequest, stream_output=True)
        await client.connect(server.get_visible_maddrs()[0])

        async def one_unary(i):
            response = await client.call_protobuf_handler(
                server.peer_id, "square", test_pb2.TestRequest(number=i), test_pb2.TestResponse
            )
            return response.number

        async def one_stream(i):
            values = []
            async for response in client.iterate_protobuf_handler(
                server.peer_id, "countdown", test_pb2.TestRequest(number=i), test_pb2.TestResponse
            ):
                values.append(response.number)
            return values

        unary_results, stream_results = await asyncio.gather(
            asyncio.gather(*(one_unary(i) for i in range(50))),
            asyncio.gather(*(one_stream(i) for i in range(1, 11))),
        )
        assert list(unary_results) == [i ** 2 for i in range(50)]
        assert list(stream_results) == [list(range(i, 0, -1)) for i in range(1, 11)]
        # all of that rode exactly one connection
        assert len(client._connections) == 1
    finally:
        await client.shutdown()
        await server.shutdown()


@pytest.mark.asyncio
async def test_identity_file_collision_detected(tmp_path):
    """Two live P2P instances must not share one identity file (capability parity:
    reference is_identity_taken, p2p_daemon.py): the second create() fails fast,
    and the identity becomes reusable once the holder shuts down."""
    path = str(tmp_path / "id.key")
    first = await P2P.create(identity_path=path)
    try:
        with pytest.raises(P2P.IdentityTakenError):
            await P2P.create(identity_path=path)
    finally:
        await first.shutdown()
    second = await P2P.create(identity_path=path)  # lock released on shutdown
    assert second.peer_id == first.peer_id  # same key file -> same identity
    await second.shutdown()


@pytest.mark.asyncio
async def test_identity_file_readonly_and_failed_create(tmp_path):
    """A pre-provisioned read-only key file works (flock on a read-only fd), and a
    create() that fails AFTER taking the lock releases it for the next attempt."""
    import os

    path = str(tmp_path / "ro.key")
    P2P.generate_identity(path)
    os.chmod(path, 0o400)
    node = await P2P.create(identity_path=path)
    await node.shutdown()

    # occupy a port, then fail a create() bound to it: the lock must be released
    blocker = await P2P.create()
    busy_port = blocker.listen_port
    with pytest.raises(OSError):
        await P2P.create(identity_path=path, listen_port=busy_port)
    retry = await P2P.create(identity_path=path)  # identity is NOT stuck "taken"
    assert retry.peer_id == node.peer_id
    await retry.shutdown()
    await blocker.shutdown()


async def test_connection_manager_trims_idle_and_redials():
    """Reference parity (go-libp2p ConnManager): past the high water mark, idle
    stream-less connections close LRU-first; a trimmed peer is re-dialed
    transparently on the next call — this is what bounds fd usage at swarm scale."""
    hub = await P2P.create(max_connections=4)

    async def echo(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
        return test_pb2.TestResponse(number=request.number + 1)

    await hub.add_protobuf_handler("echo", echo, test_pb2.TestRequest)
    spokes = []
    try:
        for _ in range(8):
            spoke = await P2P.create()
            await spoke.connect(hub.get_visible_maddrs()[0])
            spokes.append(spoke)
        await asyncio.sleep(0.1)
        live = [c for c in hub._all_connections if not c.is_closed]
        assert len(live) <= 4, f"{len(live)} live connections past the cap"

        # every spoke can still call the hub: trimmed ones re-dial transparently
        # (echo is read-only, so the ambiguous-loss retry is explicitly allowed)
        for i, spoke in enumerate(spokes):
            response = await spoke.call_protobuf_handler(
                hub.peer_id, "echo", test_pb2.TestRequest(number=i), test_pb2.TestResponse,
                idempotent=True,
            )
            assert response.number == i + 1
    finally:
        for spoke in spokes:
            await spoke.shutdown()
        await hub.shutdown()


async def test_unary_retry_gated_on_idempotency():
    """A connection that dies after the request was sent is ambiguous — the handler
    may already have run. Idempotent calls retry on a fresh connection; calls with
    side effects fail loudly instead of risking a double-applied optimizer step or
    a double-advanced decode cache (round-3 advisor, p2p.py:549)."""
    server = await P2P.create()
    calls = {"n": 0}

    async def flaky(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
        calls["n"] += 1
        if calls["n"] == 1:
            # the handler DID run; the connection dies before the response arrives
            await server._connections[context.remote_id].close()
        return test_pb2.TestResponse(number=calls["n"])

    await server.add_protobuf_handler("flaky", flaky, test_pb2.TestRequest)
    client = await P2P.create()
    await client.connect(server.get_visible_maddrs()[0])
    try:
        response = await client.call_protobuf_handler(
            server.peer_id, "flaky", test_pb2.TestRequest(number=0), test_pb2.TestResponse,
            idempotent=True,
        )
        assert response.number == 2 and calls["n"] == 2  # retried: attempt 2 answered

        calls["n"] = 0
        with pytest.raises(P2PHandlerError, match="not marked idempotent"):
            await client.call_protobuf_handler(
                server.peer_id, "flaky", test_pb2.TestRequest(number=0), test_pb2.TestResponse
            )
        assert calls["n"] == 1  # ran exactly once — no silent second application
    finally:
        await client.shutdown()
        await server.shutdown()
