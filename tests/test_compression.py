"""Codec round-trips, error bounds, adaptive selection, tensor streaming
(scope: reference tests/test_compression.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from hivemind_tpu.compression import (
    BlockwiseQuantization,
    CompressionInfo,
    CompressionType,
    Float16Compression,
    NoCompression,
    PerTensorCompression,
    Quantile8BitQuantization,
    RoleAdaptiveCompression,
    ScaledFloat16Compression,
    SizeAdaptiveCompression,
    TensorRole,
    Uniform8BitQuantization,
    deserialize_tensor,
    deserialize_tensor_stream,
    serialize_tensor,
    split_tensor_for_streaming,
)

ALL_CODECS = [
    NoCompression(),
    Float16Compression(),
    ScaledFloat16Compression(),
    Uniform8BitQuantization(),
    Quantile8BitQuantization(),
    BlockwiseQuantization(),
]


@pytest.mark.parametrize("codec", ALL_CODECS, ids=lambda c: type(c).__name__)
def test_codec_roundtrip_shape_dtype(codec):
    rng = np.random.RandomState(0)
    for shape in [(1000,), (32, 71), (2, 3, 5, 7), ()]:
        original = np.asarray(rng.randn(*shape), dtype=np.float32)
        restored = deserialize_tensor(codec.compress(original))
        assert restored.shape == original.shape
        assert restored.dtype == original.dtype


@pytest.mark.parametrize(
    "codec,max_rel_error",
    [
        (NoCompression(), 0.0),
        (Float16Compression(), 1e-3),
        (ScaledFloat16Compression(), 1e-3),
        (Uniform8BitQuantization(), 0.1),
        (Quantile8BitQuantization(), 0.1),
        (BlockwiseQuantization(), 0.05),
    ],
    ids=lambda x: type(x).__name__ if not isinstance(x, float) else str(x),
)
def test_codec_error_bounds(codec, max_rel_error):
    rng = np.random.RandomState(42)
    original = rng.randn(50_000).astype(np.float32)
    restored = deserialize_tensor(codec.compress(original))
    rel_error = np.abs(restored - original).mean() / np.abs(original).mean()
    assert rel_error <= max_rel_error, f"{type(codec).__name__}: rel_error={rel_error}"


def test_codecs_preserve_scale_outliers():
    """Blockwise quantization must adapt to per-block scale differences."""
    # seeded: the 0.01 bound sits close to the codec's typical error, and an
    # unseeded draw intermittently landed at 0.0104 (observed in-suite flake)
    rng = np.random.RandomState(0)
    original = np.concatenate([rng.randn(4096) * 1e-4, rng.randn(4096) * 1e2]).astype(np.float32)
    restored = deserialize_tensor(BlockwiseQuantization().compress(original))
    small, large = restored[:4096], restored[4096:]
    assert np.abs(small - original[:4096]).mean() < 1e-5  # small block keeps its resolution
    assert np.abs(large - original[4096:]).mean() / 1e2 < 0.01


def test_bfloat16_roundtrip():
    original = jnp.asarray(np.random.randn(128, 16), dtype=jnp.bfloat16)
    serialized = serialize_tensor(original, CompressionType.NONE)
    restored = deserialize_tensor(serialized)
    assert str(restored.dtype) == "bfloat16"
    assert np.array_equal(np.asarray(original, dtype=np.float32), np.asarray(restored, dtype=np.float32))
    # lossy codecs restore to the original dtype as well
    serialized16 = serialize_tensor(original, CompressionType.FLOAT16)
    restored16 = deserialize_tensor(serialized16)
    assert str(restored16.dtype) == "bfloat16"


def test_jax_array_input():
    original = jnp.arange(1000, dtype=jnp.float32) / 7
    scale = float(jnp.abs(original).max())  # tolerances are relative to value scale
    for ct, tol in [
        (CompressionType.NONE, 0.0),
        (CompressionType.FLOAT16, 1e-3),
        (CompressionType.BLOCKWISE_8BIT, 1e-2),
    ]:
        restored = deserialize_tensor(serialize_tensor(original, ct))
        assert np.abs(restored - np.asarray(original)).max() <= tol * scale


def test_size_adaptive_compression():
    adaptive = SizeAdaptiveCompression(
        threshold=2**10, less=NoCompression(), greater_equal=Float16Compression()
    )
    small = np.random.randn(10).astype(np.float32)
    large = np.random.randn(2**11).astype(np.float32)
    assert adaptive.compress(small, CompressionInfo.from_array(small)).compression == CompressionType.NONE
    assert adaptive.compress(large, CompressionInfo.from_array(large)).compression == CompressionType.FLOAT16


def test_role_adaptive_compression():
    adaptive = RoleAdaptiveCompression(
        gradient=Uniform8BitQuantization(), parameter=Float16Compression(), default=NoCompression()
    )
    x = np.random.randn(100).astype(np.float32)
    grad_info = CompressionInfo.from_array(x, role=TensorRole.GRADIENT)
    param_info = CompressionInfo.from_array(x, role=TensorRole.PARAMETER)
    act_info = CompressionInfo.from_array(x, role=TensorRole.ACTIVATION)
    assert adaptive.compress(x, grad_info).compression == CompressionType.UNIFORM_8BIT
    assert adaptive.compress(x, param_info).compression == CompressionType.FLOAT16
    assert adaptive.compress(x, act_info).compression == CompressionType.NONE


def test_per_tensor_compression():
    per_tensor = PerTensorCompression({"a": NoCompression(), "b": BlockwiseQuantization()})
    x = np.random.randn(100).astype(np.float32)
    assert per_tensor.compress(x, CompressionInfo.from_array(x, key="a")).compression == CompressionType.NONE
    assert per_tensor.compress(x, CompressionInfo.from_array(x, key="b")).compression == CompressionType.BLOCKWISE_8BIT


async def test_tensor_streaming_roundtrip():
    originals = [
        np.random.randn(100_000).astype(np.float32),
        np.random.randn(10).astype(np.float32),
        np.random.randn(333, 3).astype(np.float32),
    ]
    chunks = []
    for original in originals:
        serialized = serialize_tensor(original, CompressionType.FLOAT16)
        chunks.extend(split_tensor_for_streaming(serialized, chunk_size_bytes=2**16))

    async def stream():
        for chunk in chunks:
            yield [chunk]

    restored = await deserialize_tensor_stream(stream())
    assert len(restored) == len(originals)
    for orig, rest in zip(originals, restored):
        assert np.allclose(orig, rest, rtol=1e-3, atol=1e-3)


async def test_tensor_streaming_truncated_fails():
    serialized = serialize_tensor(np.random.randn(100_000).astype(np.float32))
    chunks = split_tensor_for_streaming(serialized, chunk_size_bytes=2**16)

    async def stream():
        for chunk in chunks[:-1]:
            yield [chunk]

    with pytest.raises(ValueError, match="mid-tensor"):
        await deserialize_tensor_stream(stream())


def test_pallas_blockwise_kernels_match_jnp():
    """The Pallas TPU kernels (run here in interpret mode) must produce bit-identical
    codes/absmax/dequant to the fused-jnp host path the codec uses on CPU."""
    import jax
    from hivemind_tpu.ops.pallas_quantization import (
        pallas_blockwise_dequantize,
        pallas_blockwise_quantize,
    )
    from hivemind_tpu.ops.quantization import blockwise_dequantize, blockwise_quantize

    rng = np.random.RandomState(7)
    flat = rng.randn(3 * 4096).astype(np.float32)  # 3 rows: exercises row padding
    codes_p, absmax_p = pallas_blockwise_quantize(flat, interpret=True)
    codes_j, absmax_j = blockwise_quantize(flat)
    np.testing.assert_array_equal(np.asarray(codes_p), np.asarray(codes_j))
    np.testing.assert_allclose(np.asarray(absmax_p), np.asarray(absmax_j))
    out_p = pallas_blockwise_dequantize(codes_p, absmax_p, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out_p), np.asarray(blockwise_dequantize(codes_j, absmax_j))
    )
