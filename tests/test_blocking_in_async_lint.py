"""Tier-1 guard for the blocking-in-async lint (ISSUE 8 satellite): new
``time.sleep`` / blocking file IO / sync socket calls inside ``async def``
under p2p/dht/averaging/moe fail the suite — the event-loop watchdog catches
such stalls at runtime, this keeps them from being merged at all."""

import sys
import textwrap
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import check_blocking_in_async as lint  # noqa: E402


def test_no_new_blocking_calls_in_async_defs():
    new, stale = lint.check()
    assert not new, (
        "blocking call(s) inside async def on the swarm's event loop "
        "(they stall every RPC/matchmaking/stream of this peer at once):\n  "
        + "\n  ".join(new)
        + "\nFix: await asyncio.sleep / run_in_executor / loop transports. "
        "Only reviewed legacy sites belong in ALLOWLIST."
    )
    # stale entries are a warning, not a failure — but surface them
    for entry in stale:
        print(f"stale allowlist entry: {entry}")


def test_lint_detects_each_rule(tmp_path):
    """The lint must actually catch what it claims to catch (and not flag the
    executor pattern), or the guard above is a no-op."""
    package = tmp_path / "pkg"
    for tree in lint.SCANNED_TREES:
        (package / tree).mkdir(parents=True)
        (package / tree / "__init__.py").write_text("")
    (package / "p2p" / "bad.py").write_text(
        textwrap.dedent(
            """
            import asyncio
            import socket
            import time

            async def stalls_everything():
                time.sleep(1.0)          # time-sleep
                data = open("/tmp/x").read()   # blocking-io
                conn = socket.create_connection(("h", 1))  # sync-socket
                return data, conn

            async def fine():
                await asyncio.sleep(0.1)

                def _work():            # executor pattern: sync def inside async
                    time.sleep(1.0)
                    with open("/tmp/y") as f:
                        return f.read()

                return await asyncio.get_event_loop().run_in_executor(None, _work)

            def also_fine():
                time.sleep(1.0)
                return open("/tmp/z")
            """
        )
    )
    new, _stale = lint.check(package_root=package)
    kinds = sorted(line.split("[")[1].split("]")[0] for line in new)
    assert kinds == ["blocking-io", "sync-socket", "time-sleep"], new
    assert all("stalls_everything" in line for line in new), new
