"""DecentralizedAverager end-to-end: matchmaking over a real DHT swarm, group
all-reduce correctness vs numpy, weights, client/aux modes, two-phase trigger, state
download, rebucketing (scope: reference tests/test_averaging.py)."""

import time

import numpy as np
import pytest

from hivemind_tpu.averaging import DecentralizedAverager
from hivemind_tpu.averaging.control import AveragingStage
from hivemind_tpu.dht import DHT
from hivemind_tpu.utils.timed_storage import get_dht_time

from swarm_utils import launch_dht_swarm, shutdown_all


def make_averagers(dhts, n_tensors=2, prefix="avgtest", **kwargs):
    averagers = []
    for i, dht in enumerate(dhts):
        rng = np.random.RandomState(i)
        tensors = [rng.randn(123).astype(np.float32), rng.randn(3, 5).astype(np.float32)][:n_tensors]
        averagers.append(
            DecentralizedAverager(
                tensors, dht, prefix=prefix, start=True,
                min_matchmaking_time=1.0, request_timeout=1.0,
                sender_timeout=5.0, reducer_timeout=10.0,
                **kwargs,
            )
        )
    return averagers



def test_averaging_basic_group():
    dhts = launch_dht_swarm(4)
    averagers = make_averagers(dhts, target_group_size=4)
    try:
        originals = [[t.copy() for t in a._averaged_tensors] for a in averagers]
        controls = [
            a.step(gather={"rank": i}, wait=False, timeout=30)
            for i, a in enumerate(averagers)
        ]
        results = [c.result(timeout=60) for c in controls]
        # every peer sees everyone's gathered metadata
        for result in results:
            assert result is not None and len(result) == 4
            assert sorted(info["rank"] for info in result.values()) == [0, 1, 2, 3]
        # all tensors converge to the elementwise mean
        for k in range(2):
            expected = np.mean([originals[i][k] for i in range(4)], axis=0)
            for averager in averagers:
                with averager.get_tensors() as tensors:
                    assert np.allclose(tensors[k], expected, atol=1e-4)
        # rebucketing happened deterministically and identically per group
        bits = {a.get_group_bits() for a in averagers}
        assert all(len(b) == 0 for b in bits)  # nbits=0 → no-op, but API works
    finally:
        shutdown_all(averagers, dhts)


def test_averaging_weighted():
    dhts = launch_dht_swarm(2)
    averagers = make_averagers(dhts, target_group_size=2)
    try:
        originals = [[t.copy() for t in a._averaged_tensors] for a in averagers]
        weights = [1.0, 3.0]
        controls = [
            a.step(weight=w, wait=False, timeout=30) for a, w in zip(averagers, weights)
        ]
        for control in controls:
            control.result(timeout=60)
        expected = [
            (originals[0][k] * 1.0 + originals[1][k] * 3.0) / 4.0 for k in range(2)
        ]
        for averager in averagers:
            with averager.get_tensors() as tensors:
                for k in range(2):
                    assert np.allclose(tensors[k], expected[k], atol=1e-4)
    finally:
        shutdown_all(averagers, dhts)


def test_averaging_client_mode():
    dhts = launch_dht_swarm(3)
    averagers = make_averagers(dhts[:2], target_group_size=3)
    client = make_averagers([dhts[2]], target_group_size=3, client_mode=True)[0]
    averagers.append(client)
    try:
        originals = [[t.copy() for t in a._averaged_tensors] for a in averagers]
        controls = [a.step(wait=False, timeout=30) for a in averagers]
        for control in controls:
            control.result(timeout=60)
        expected = [np.mean([originals[i][k] for i in range(3)], axis=0) for k in range(2)]
        for averager in averagers:  # client's tensors must also be averaged
            with averager.get_tensors() as tensors:
                for k in range(2):
                    assert np.allclose(tensors[k], expected[k], atol=1e-4)
    finally:
        shutdown_all(averagers, dhts)


def test_averaging_two_phase_trigger():
    dhts = launch_dht_swarm(2)
    averagers = make_averagers(dhts, target_group_size=2)
    try:
        controls = [
            a.step(wait=False, require_trigger=True, timeout=30) for a in averagers
        ]
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not all(
            c.stage in (AveragingStage.AWAITING_TRIGGER,) for c in controls
        ):
            time.sleep(0.1)
        assert all(not c.began_allreduce for c in controls)
        for control in controls:
            control.allow_allreduce()
        for control in controls:
            assert control.result(timeout=60) is not None
            assert control.began_allreduce
    finally:
        shutdown_all(averagers, dhts)


def test_averaging_no_group_fails_cleanly():
    dhts = launch_dht_swarm(1)
    averager = make_averagers(dhts, target_group_size=2)[0]
    try:
        with pytest.raises(Exception):
            averager.step(timeout=4, allow_retries=False)
    finally:
        shutdown_all([averager], dhts)


def test_state_download():
    dhts = launch_dht_swarm(2)
    averagers = make_averagers(dhts, target_group_size=2, declare_state_period=0.5)
    try:
        time.sleep(1.5)  # let state declarations propagate
        result = averagers[1].load_state_from_peers(timeout=20)
        assert result is not None
        metadata, tensors = result
        with averagers[0].get_tensors() as donor_tensors:
            assert len(tensors) == len(donor_tensors)
            for downloaded, donor in zip(tensors, donor_tensors):
                assert np.allclose(downloaded, donor, atol=1e-6)
    finally:
        shutdown_all(averagers, dhts)


def test_group_bits_rebucketing():
    dhts = launch_dht_swarm(2)
    averagers = make_averagers(dhts, target_group_size=2, initial_group_bits="00")
    try:
        controls = [a.step(wait=False, timeout=30) for a in averagers]
        for control in controls:
            control.result(timeout=60)
        # both peers derived their new bucket from the same group id
        bits = [a.get_group_bits() for a in averagers]
        assert all(len(b) == 2 and set(b) <= {"0", "1"} for b in bits)
    finally:
        shutdown_all(averagers, dhts)


def test_oversized_swarm_splits_into_groups():
    """More peers than target_group_size: matchmaking forms MULTIPLE groups and
    every peer still completes its round (reference test_averaging grouping
    scenarios)."""
    dhts = launch_dht_swarm(6)
    # min_group_size=3 forbids a 3+2+1 split that would strand the sixth peer:
    # undersized groups disband and retry (with jitter) until two 3-groups form
    averagers = make_averagers(dhts, target_group_size=3, min_group_size=3)
    try:
        controls = [a.step(gather={"i": i}, wait=False, timeout=60) for i, a in enumerate(averagers)]
        groups = []
        for control in controls:
            result = control.result(timeout=120)
            assert result is not None
            assert len(result) == 3
            groups.append(frozenset(result))
        # the groups PARTITION the swarm: every peer in exactly one group, and
        # groupmates agree on the membership
        distinct = set(groups)
        assert len(distinct) >= 2, f"six peers cannot fit one group of three: {distinct}"
        seen = [peer for group in distinct for peer in group]
        assert len(seen) == 6 and len(set(seen)) == 6, distinct
    finally:
        shutdown_all(averagers, dhts)


def test_aux_peer_helps_averaging():
    """An AUX averager (reduces-only, zero weight) joins a round: the NODE peers
    converge to the mean of THEIR tensors; the aux contributes no values."""
    dhts = launch_dht_swarm(3)
    rng = np.random.RandomState(0)
    values = [rng.randn(200).astype(np.float32) for _ in range(2)]
    common = dict(
        prefix="auxavg", start=True, target_group_size=3, min_group_size=3,
        min_matchmaking_time=1.0, request_timeout=1.0,
        sender_timeout=5.0, reducer_timeout=10.0,
    )
    nodes = [
        DecentralizedAverager([values[i].copy()], dhts[i], **common) for i in range(2)
    ]
    aux = DecentralizedAverager(
        [np.zeros(200, np.float32)], dhts[2], auxiliary=True, **common
    )
    try:
        controls = [a.step(wait=False, timeout=40) for a in nodes + [aux]]
        for control in controls:
            assert control.result(timeout=90) is not None
        expected = (values[0] + values[1]) / 2  # aux weight 0: not in the average
        for node in nodes:
            with node.get_tensors() as tensors:
                assert np.allclose(tensors[0], expected, atol=1e-4)
    finally:
        shutdown_all(nodes + [aux], dhts)


def test_step_control_cancel_before_trigger():
    """A scheduled-but-cancelled step must release its group slot cleanly: the
    remaining peers still need a partner, so both cancel here and both steps report
    failure without wedging the averagers (user-level analog of Fault.CANCEL)."""
    dhts = launch_dht_swarm(2)
    averagers = [
        DecentralizedAverager(
            [np.ones(64, np.float32) * i], dht, prefix="cancel_test", start=True,
            target_group_size=2, min_matchmaking_time=0.5,
        )
        for i, dht in enumerate(dhts)
    ]
    try:
        controls = [a.step(wait=False, require_trigger=True, timeout=15) for a in averagers]
        for control in controls:
            assert not control.triggered and not control.began_allreduce
            assert control.cancel()
            assert control.cancelled
        # a subsequent un-cancelled round on the same averagers still works
        controls = [a.step(wait=False, timeout=30) for a in averagers]
        results = [c.result(timeout=45) for c in controls]
        assert all(results), results
        for averager, expected in zip(averagers, (0.5, 0.5)):
            with averager.get_tensors() as tensors:
                np.testing.assert_allclose(tensors[0], expected, atol=1e-6)
    finally:
        shutdown_all(averagers, dhts)


def test_adaptive_matchmaking_lead_time_math():
    """suggested_lead_time grows multiplicatively on window-expired failures,
    shrinks again on successes, tracks observed fill latency, and is capped
    (VERDICT r3 #5 — bare averager users must self-heal under contention)."""
    from hivemind_tpu.averaging.matchmaking import Matchmaking

    mm = Matchmaking.__new__(Matchmaking)
    mm.min_matchmaking_time = 1.0
    mm.fill_latency_ema = None
    mm._lead_backoff = 1.0
    mm._others_observed = False

    assert mm.suggested_lead_time() == 1.0
    # a peer that starts before its swarm ratchets while alone (harmless —
    # nobody to match with), but FIRST CONTACT discards the solo-era backoff so
    # the first real group forms at the base lead time (advisor r4)
    for _ in range(6):
        mm._record_round_outcome(None)
    assert mm._lead_backoff > 1.0
    mm._note_others_observed()
    assert mm._lead_backoff == 1.0 and mm.suggested_lead_time() == 1.0
    mm._note_others_observed()  # later observations never reset again
    mm._record_round_outcome(None)  # window expired under contention
    mm._record_round_outcome(None)
    assert mm.suggested_lead_time() == 4.0  # 1.0 * 2 * 2
    for _ in range(10):
        mm._record_round_outcome(None)
    assert mm._lead_backoff == 16.0  # backoff itself is capped at 16x
    assert mm.suggested_lead_time() == 16.0  # min(1.0 * 16, cap=max(8x1, 30)=30)

    # a successful round at 5s observed latency: backoff halves, EMA kicks in
    mm._record_round_outcome(5.0)
    assert mm.fill_latency_ema == 5.0
    assert mm.suggested_lead_time() == 30.0  # 1.25*5 * backoff(8) = 50 -> capped at 30
    for _ in range(6):
        mm._record_round_outcome(0.4)  # fast fills: backoff decays to 1, EMA drops
    assert mm._lead_backoff == 1.0
    assert 1.0 <= mm.suggested_lead_time() <= 2.0  # floor is min_matchmaking_time


def test_adaptive_lead_recovers_from_too_short_window():
    """Four peers with an absurdly short 0.05s matchmaking window: the first
    attempts expire, the adaptive backoff stretches the window, and the step
    succeeds within its retry budget — no operator re-sizing (VERDICT r3 #5)."""
    dhts = launch_dht_swarm(4)
    averagers = []
    try:
        for i, dht in enumerate(dhts):
            tensors = [np.full(16, float(i), np.float32)]
            averagers.append(
                DecentralizedAverager(
                    tensors, dht, prefix="adaptlead", start=True,
                    target_group_size=4, min_group_size=4,
                    min_matchmaking_time=0.05, request_timeout=1.0,
                )
            )
        controls = [a.step(wait=False, timeout=60) for a in averagers]
        results = [c.result(timeout=90) for c in controls]
        assert all(r is not None for r in results)
        for averager in averagers:
            with averager.get_tensors() as tensors:
                np.testing.assert_allclose(tensors[0], np.full(16, 1.5, np.float32), atol=1e-5)
        # NOTE: whether any peer actually had to stretch depends on host load (a
        # quiet loopback can fill even a 50 ms window first try), so the adaptive
        # mechanics themselves are asserted deterministically in
        # test_adaptive_matchmaking_lead_time_math; this test pins the user-visible
        # contract — an absurdly short window still converges within one step call.
    finally:
        for averager in averagers:
            averager.shutdown()
        for dht in dhts:
            dht.shutdown()
