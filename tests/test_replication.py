"""ISSUE 13: serving survives replicas dying and clients misbehaving —
replica-set DHT records, scorecard-balanced routing with breaker-aware
failover, hedged requests with exact loser bookkeeping, per-client fair-share
admission, and the hot-expert replication control loop."""

from __future__ import annotations

import asyncio
import time
from typing import AsyncIterator, List, Optional

import numpy as np
import pytest

from hivemind_tpu.moe.expert_uid import ExpertInfo, ReplicaInfo
from hivemind_tpu.moe.server.dht_handler import (
    expert_info_from_entry,
    make_expert_record,
    parse_expert_replicas,
)
from hivemind_tpu.p2p import PeerID
from hivemind_tpu.utils.crypto import Ed25519PrivateKey
from hivemind_tpu.utils.timed_storage import ValueWithExpiration


def _peer() -> PeerID:
    return PeerID.from_private_key(Ed25519PrivateKey())


# ------------------------------------------------------------------ records


def test_replica_record_forms():
    """Every historical leaf form parses: bare peer, peer|codec, and the
    ISSUE-13 subkey dictionary; malformed members are skipped, duplicates
    deduped, order deterministic (sorted by peer id)."""
    a, b = _peer(), _peer()
    # legacy plain string (one replica)
    [replica] = parse_expert_replicas(make_expert_record(a.to_base58(), "float16"))
    assert replica == ReplicaInfo(a, "float16")
    [replica] = parse_expert_replicas(a.to_base58())
    assert replica == ReplicaInfo(a, None)
    # subkey dictionary: the multi-value replica set
    entry = {
        a.to_base58(): ValueWithExpiration(make_expert_record(a.to_base58(), "float16"), 10.0),
        b.to_base58(): ValueWithExpiration(make_expert_record(b.to_base58(), "none"), 11.0),
        "junk": ValueWithExpiration("not!!a@@peer", 12.0),
        "more_junk": ValueWithExpiration(12345, 13.0),
    }
    replicas = parse_expert_replicas(entry)
    assert len(replicas) == 2
    assert replicas == sorted(replicas, key=lambda r: r.peer_id.to_base58())
    assert {r.peer_id for r in replicas} == {a, b}
    # malformed whole value
    assert parse_expert_replicas(None) == []
    assert parse_expert_replicas(42) == []


def test_expert_info_from_entry_carries_full_set():
    a, b = sorted((_peer(), _peer()), key=lambda p: p.to_base58())
    entry = {
        a.to_base58(): ValueWithExpiration(make_expert_record(a.to_base58(), "float16"), 10.0),
        b.to_base58(): ValueWithExpiration(make_expert_record(b.to_base58()), 11.0),
    }
    info = expert_info_from_entry("grid.0", entry)
    assert info is not None and info.uid == "grid.0"
    assert info.peer_id == a  # deterministic primary; clients re-select
    assert len(info.replica_set) == 2
    # single-replica ExpertInfo still reports a non-empty replica set
    solo = ExpertInfo("grid.0", a, "none")
    assert solo.replica_set == (ReplicaInfo(a, "none"),)
    assert expert_info_from_entry("grid.0", {"x": ValueWithExpiration("0Il!bad", 1.0)}) is None


# ------------------------------------------------------------------ admission


def test_fair_share_admission_bucket():
    from hivemind_tpu.moe.server.admission import ClientOverBudgetError, FairShareAdmission
    from hivemind_tpu.moe.server.task_pool import ServerOverloadedError

    clock = [0.0]
    admission = FairShareAdmission(rate_per_s=10.0, burst=20.0, clock=lambda: clock[0])
    # the burst drains, then the typed shed
    for _ in range(5):
        admission.admit("alice", 4.0)
    with pytest.raises(ClientOverBudgetError) as info:
        admission.admit("alice", 4.0)
    assert isinstance(info.value, ServerOverloadedError)  # existing shed contract
    # other clients keep flowing (their own bucket)
    admission.admit("bob", 4.0)
    # refill: 1 second restores 10 tokens
    clock[0] += 1.0
    admission.admit("alice", 10.0)
    with pytest.raises(ClientOverBudgetError):
        admission.admit("alice", 1.0)
    assert admission.tokens("alice") < 1.0


def test_admission_is_typed_overload_and_bounded():
    from hivemind_tpu.moe.server.admission import ClientOverBudgetError, FairShareAdmission
    from hivemind_tpu.telemetry.serving import is_overload_error

    assert is_overload_error(ClientOverBudgetError("client x over budget"))
    # recognized across the RPC boundary by type-name text, like pool sheds
    assert is_overload_error(RuntimeError("ClientOverBudgetError: client x over budget"))
    admission = FairShareAdmission(rate_per_s=1.0, max_clients=4)
    for index in range(10):
        admission.admit(f"client{index}", 0.1)
    assert len(admission) <= 4  # identity cycling cannot grow the map


# ------------------------------------------------------------------ hedging (stubbed replicas)


class _StubExpert:
    """Builds a RemoteExpert whose per-replica RPC is scripted: each replica's
    behavior is a callable returning a result, raising, or hanging forever."""

    def __init__(self, behaviors, uid="stub.0", hedging=True):
        import types

        from hivemind_tpu.moe.client.expert import RemoteExpert

        self.replicas = [ReplicaInfo(_peer(), "none") for _ in behaviors]
        self.by_peer = {
            replica.peer_id: behavior for replica, behavior in zip(self.replicas, behaviors)
        }
        info = ExpertInfo(uid, self.replicas[0].peer_id, "none", tuple(self.replicas))
        self.calls: List[PeerID] = []
        self.cancelled: List[PeerID] = []
        outer = self
        p2p = types.SimpleNamespace(peer_id=_peer())
        expert = RemoteExpert(info, p2p, seed=7, hedging=hedging)

        async def _call_replica(method, replica, tensors, metadata=b""):
            outer.calls.append(replica.peer_id)
            try:
                return await outer.by_peer[replica.peer_id]()
            except asyncio.CancelledError:
                outer.cancelled.append(replica.peer_id)
                raise

        expert._call_replica = _call_replica
        self.expert = expert


def _warm_replica(uid: str, peer: PeerID, latency: float = 0.01, n: int = 20):
    from hivemind_tpu.telemetry.serving import SCORECARDS

    for _ in range(n):
        SCORECARDS.record_replica(uid, peer.to_base58(), latency, ok=True)


async def test_hedge_fires_and_loser_is_clean():
    """The satellite contract: when the primary stalls past its scorecard p95,
    a hedge races the second replica; the winner's result returns, the loser is
    CANCELLED and never registers a scorecard failure or a breaker strike."""
    from hivemind_tpu.moe.client.call_many import EXPERT_BREAKERS
    from hivemind_tpu.moe.client.expert import replica_breaker_key
    from hivemind_tpu.telemetry.serving import SCORECARDS

    async def hang():
        await asyncio.sleep(3600)

    async def fast():
        await asyncio.sleep(0.005)
        return [np.ones(2, np.float32)]

    stub = _StubExpert([hang, fast], uid="hedge.0")
    slow_peer, fast_peer = (replica.peer_id for replica in stub.replicas)
    # warmed quantiles: the primary looks fast (small p95), so the stall crosses it
    _warm_replica("hedge.0", slow_peer, latency=0.01)
    result = await stub.expert._call("forward", [np.zeros(2, np.float32)])
    assert np.allclose(result[0], 1.0)
    assert stub.calls[0] == slow_peer and fast_peer in stub.calls  # hedge launched
    await asyncio.sleep(0.05)  # let the loser's CancelledError deliver
    assert stub.cancelled == [slow_peer]  # loser cancelled...
    card = SCORECARDS.card("hedge.0")
    slow_stats = card["replicas"][slow_peer.to_base58()]
    assert slow_stats["failures"] == 0 and slow_stats["sheds"] == 0  # ...with NO failure
    assert slow_stats.get("hedge_losses", 0) == 1  # censored latency only
    assert replica_breaker_key("hedge.0", slow_peer) not in EXPERT_BREAKERS  # no strike
    # uid-level outcome: one clean success
    assert card["ok"] == 1 and card["failures"] == 0
    from hivemind_tpu.telemetry.serving import REGISTRY

    metric = REGISTRY.get("hivemind_moe_hedge_total")
    outcomes = {",".join(k): c.value for k, c in metric.series()}
    assert outcomes.get("fired", 0) >= 1 and outcomes.get("hedge_won", 0) >= 1


async def test_no_hedge_while_cold_or_disabled():
    async def slowish():
        await asyncio.sleep(0.05)
        return [np.zeros(1, np.float32)]

    async def fast():
        return [np.ones(1, np.float32)]

    # cold scorecards: no p95, no hedge — the primary's answer is awaited
    stub = _StubExpert([slowish, fast], uid="cold.0")
    await stub.expert._call("forward", [np.zeros(1, np.float32)])
    assert len(stub.calls) == 1
    # warmed but hedging disabled
    stub = _StubExpert([slowish, fast], uid="nohedge.0", hedging=False)
    _warm_replica("nohedge.0", stub.replicas[0].peer_id, latency=0.001)
    await stub.expert._call("forward", [np.zeros(1, np.float32)])
    assert len(stub.calls) == 1


async def test_shed_fails_over_to_next_replica():
    """Satellite: a typed shed on one replica fails over instead of failing
    the call — and the shed lands on the REPLICA's card, not the uid outcome."""
    from hivemind_tpu.moe.server.task_pool import ServerOverloadedError
    from hivemind_tpu.telemetry.serving import REGISTRY, SCORECARDS

    async def shedding():
        raise ServerOverloadedError("pool full; request shed")

    async def fast():
        return [np.ones(1, np.float32)]

    stub = _StubExpert([shedding, fast], uid="shed.0")
    result = await stub.expert._call("forward", [np.zeros(1, np.float32)])
    assert np.allclose(result[0], 1.0)
    assert len(stub.calls) == 2
    card = SCORECARDS.card("shed.0")
    assert card["ok"] == 1 and card["sheds"] == 0  # the LOGICAL call succeeded
    assert card["replicas"][stub.replicas[0].peer_id.to_base58()]["sheds"] == 1
    metric = REGISTRY.get("hivemind_moe_replica_failover_total")
    assert sum(c.value for _k, c in metric.series()) >= 1


async def test_single_replica_shed_propagates_exactly_as_before():
    """With no second replica the PR 8 contract is untouched: the typed shed
    reaches the caller, the scorecard counts a shed, the uid breaker strikes."""
    from hivemind_tpu.moe.client.call_many import EXPERT_BREAKERS
    from hivemind_tpu.moe.server.task_pool import ServerOverloadedError
    from hivemind_tpu.telemetry.serving import SCORECARDS

    async def shedding():
        raise ServerOverloadedError("pool full; request shed")

    stub = _StubExpert([shedding], uid="solo.0")
    for _ in range(2):
        with pytest.raises(ServerOverloadedError):
            await stub.expert._call("forward", [np.zeros(1, np.float32)])
    card = SCORECARDS.card("solo.0")
    assert card["sheds"] == 2
    assert "solo.0" in EXPERT_BREAKERS  # two strikes trip the uid breaker


async def test_deterministic_failure_does_not_fail_over():
    """A deterministic handler error (bad input → ValueError) would fail on
    every replica: no failover, the error surfaces once."""

    async def broken():
        raise RuntimeError("ValueError: deliberate schema mismatch")  # not replica-gone

    async def fast():
        return [np.ones(1, np.float32)]

    stub = _StubExpert([broken, fast], uid="det.0")
    with pytest.raises(RuntimeError, match="schema mismatch"):
        await stub.expert._call("forward", [np.zeros(1, np.float32)])
    assert len(stub.calls) == 1


async def test_decode_sessions_stick_to_winning_replica():
    """Decode prefill may balance/fail over; continuations are pinned to the
    replica that holds the KV cache."""

    async def fast():
        return [np.ones(1, np.float32)]

    stub = _StubExpert([fast, fast], uid="dec.0")
    await stub.expert._call("decode", [np.zeros(1, np.float32)], b"",
                            session="s1", session_reset=True)
    pinned = stub.calls[-1]
    for _ in range(3):
        await stub.expert._call("decode", [np.zeros(1, np.float32)], b"",
                                session="s1", session_reset=False)
    assert all(peer == pinned for peer in stub.calls)


def test_cold_replica_choice_is_seeded():
    """Satellite: the initial replica pick is seeded-random, not 'first
    declared value' — different seeds spread, the same seed replays."""
    import types

    from hivemind_tpu.moe.client.expert import RemoteExpert

    replicas = tuple(ReplicaInfo(_peer(), None) for _ in range(4))
    info = ExpertInfo("seeded.0", replicas[0].peer_id, None, replicas)
    p2p = types.SimpleNamespace(peer_id=_peer())

    def first_choice(seed):
        return RemoteExpert(info, p2p, seed=seed)._replica_order()[0].peer_id

    assert first_choice(1) == first_choice(1)  # deterministic per seed
    firsts = {first_choice(seed).to_base58() for seed in range(12)}
    assert len(firsts) > 1  # and spread across the set, not always replicas[0]


# ------------------------------------------------------------------ mux + pool


async def test_mux_reset_cancels_inbound_handler():
    """Hedge-loser cancellation propagates: the client's RESET must cancel the
    server's still-running handler (the losing server stops computing)."""
    from hivemind_tpu.p2p import P2P, P2PContext
    from hivemind_tpu.proto import test_pb2

    server = await P2P.create()
    client = await P2P.create()
    entered = asyncio.Event()
    cancelled = asyncio.Event()

    async def slow(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
        entered.set()
        try:
            await asyncio.sleep(3600)
        except asyncio.CancelledError:
            cancelled.set()
            raise
        return test_pb2.TestResponse(number=0)

    await server.add_protobuf_handler("slow", slow, test_pb2.TestRequest)
    await client.connect(server.get_visible_maddrs()[0])
    call = asyncio.ensure_future(client.call_protobuf_handler(
        server.peer_id, "slow", test_pb2.TestRequest(number=1), test_pb2.TestResponse
    ))
    await asyncio.wait_for(entered.wait(), 10)
    call.cancel()
    with pytest.raises(asyncio.CancelledError):
        await call
    await asyncio.wait_for(cancelled.wait(), 10)  # the server STOPPED computing
    await client.shutdown()
    await server.shutdown()


async def test_pop_batch_skips_cancelled_tasks():
    """A queued task whose caller gave up (future done) is dropped at drain
    time instead of burning a device-batch slot."""
    from hivemind_tpu.moe.server.task_pool import TaskPool

    pool = TaskPool(lambda x: [x * 2], "cancel_test", max_batch_size=8)

    async def submit(value):
        return await pool.submit_task(np.full((1, 2), value, np.float32))

    keeper = asyncio.ensure_future(submit(1.0))
    loser = asyncio.ensure_future(submit(2.0))
    await asyncio.sleep(0.01)  # both enqueued
    loser.cancel()
    with pytest.raises(asyncio.CancelledError):
        await loser
    batch = pool.pop_batch()
    assert [task.args[0][0, 0] for task in batch] == [1.0]
    pool.process_batch(batch)
    [out] = await keeper
    assert np.allclose(out, 2.0)


# ------------------------------------------------------------------ end to end


def test_replicated_expert_survives_replica_death():
    """Two servers declare the same uid → one multi-value record; the client
    balances across both, and killing one replica is never client-visible."""
    import optax

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe import RemoteExpert, Server, get_experts

    dht1 = DHT(start=True)
    maddrs = [str(m) for m in dht1.get_visible_maddrs()]
    s1 = Server.create(expert_uids=["reptest.0"], expert_cls="ffn", hidden_dim=16,
                       dht=dht1, start=True, optim_factory=lambda: optax.sgd(1e-3))
    dht2 = DHT(initial_peers=maddrs, start=True)
    s2 = Server.create(expert_uids=["reptest.0"], expert_cls="ffn", hidden_dim=16,
                       dht=dht2, start=True, optim_factory=lambda: optax.sgd(1e-3))
    client_dht = DHT(initial_peers=maddrs, start=True)
    try:
        info = None
        for _ in range(30):
            [info] = get_experts(client_dht, ["reptest.0"])
            if info is not None and len(info.replica_set) == 2:
                break
            time.sleep(0.5)
        assert info is not None and len(info.replica_set) == 2, info
        expert = RemoteExpert(info, client_dht.node.p2p)
        x = np.random.RandomState(0).randn(2, 16).astype(np.float32)
        expert.forward_np(x)
        s1.shutdown()
        dht1.shutdown()
        for _ in range(5):
            expert.forward_np(x)  # transparent failover: no exception = pass
    finally:
        s2.shutdown()
        dht2.shutdown()
        client_dht.shutdown()


def test_replication_manager_acquires_hot_expert():
    """The full control loop: traffic makes an expert hot → replica_wanted
    advert → a replica-slot server fetches spec+state (digest-verified), serves
    and declares — the client then resolves a two-replica set with bit-close
    outputs on both."""
    import optax

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe import RemoteExpert, Server, get_experts
    from hivemind_tpu.moe.expert_uid import ExpertInfo
    from hivemind_tpu.moe.server.replication import ReplicationPolicy

    policy = ReplicationPolicy(qps_threshold=1.0, occupancy_threshold=0.5,
                               max_replicas=2, period=1.0)
    dht1 = DHT(start=True)
    maddrs = [str(m) for m in dht1.get_visible_maddrs()]
    s1 = Server.create(expert_uids=["hotgrid.0"], expert_cls="ffn", hidden_dim=16,
                       dht=dht1, start=True, optim_factory=lambda: optax.sgd(1e-3),
                       replicate_hot_experts=True, replication_policy=policy)
    dht2 = DHT(initial_peers=maddrs, start=True)
    s2 = Server.create(dht=dht2, start=True, replica_slots=1, replication_policy=policy,
                       replication_watch_grids=["hotgrid"],
                       optim_factory=lambda: optax.sgd(1e-3))
    client_dht = DHT(initial_peers=maddrs, start=True)
    try:
        [info] = get_experts(client_dht, ["hotgrid.0"])
        assert info is not None
        expert = RemoteExpert(info, client_dht.node.p2p)
        x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            for _ in range(5):
                expert.forward_np(x)
            [info] = get_experts(client_dht, ["hotgrid.0"])
            if info is not None and len(info.replica_set) == 2:
                break
            time.sleep(0.5)
        assert info is not None and len(info.replica_set) == 2, "replica never acquired"
        outputs = []
        for replica in info.replica_set:
            solo = ExpertInfo("hotgrid.0", replica.peer_id, replica.compression, None)
            outputs.append(RemoteExpert(solo, client_dht.node.p2p).forward_np(x)[0])
        # backward traffic may have stepped the donor between transfer and
        # check: replicas must be CLOSE (weights moved verbatim), not stale
        np.testing.assert_allclose(outputs[0], outputs[1], atol=1e-3)
    finally:
        s1.shutdown()
        s2.shutdown()
        dht1.shutdown()
        dht2.shutdown()
        client_dht.shutdown()


def test_admission_shed_feeds_breakers_and_scorecards():
    """Fair-share sheds over real RPC stay typed end to end: the client's
    scorecard counts sheds, the uid breaker accumulates them — exactly the
    PR 8 shed contract."""
    import optax

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe import RemoteExpert, Server, get_experts
    from hivemind_tpu.moe.client.call_many import EXPERT_BREAKERS
    from hivemind_tpu.telemetry.serving import SCORECARDS, is_overload_error

    dht1 = DHT(start=True)
    s1 = Server.create(expert_uids=["admtest.0"], expert_cls="ffn", hidden_dim=16,
                       dht=dht1, start=True, optim_factory=lambda: optax.sgd(1e-3),
                       client_rate=8.0, client_burst=16.0)
    client_dht = DHT(initial_peers=[str(m) for m in dht1.get_visible_maddrs()], start=True)
    try:
        [info] = get_experts(client_dht, ["admtest.0"])
        expert = RemoteExpert(info, client_dht.node.p2p)
        x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
        ok = shed = 0
        for _ in range(10):
            try:
                expert.forward_np(x)
                ok += 1
            except Exception as e:
                assert is_overload_error(e), repr(e)
                shed += 1
        assert ok >= 3 and shed >= 2  # burst 16 = 4 requests of 4 samples
        assert SCORECARDS.card("admtest.0")["sheds"] == shed
        assert "admtest.0" in EXPERT_BREAKERS
    finally:
        s1.shutdown()
        dht1.shutdown()
        client_dht.shutdown()


@pytest.mark.chaos
def test_serving_churn_smoke():
    """The run_chaos_soak --serving phase, short: stall → kill → restart one
    replica mid-traffic; >=1 hedge fired, zero client-visible failures,
    breakers recovered (see hivemind_cli/run_chaos_soak.py)."""
    from hivemind_tpu.hivemind_cli.run_chaos_soak import run_serving_churn

    report = run_serving_churn(duration=30.0, seed=0)
    assert report["checks"]["hedge_fired"], report
    assert report["checks"]["zero_client_visible_failures"], report
    assert report["checks"]["breakers_recovered"], report
    assert report["ok"], report
