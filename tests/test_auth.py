"""Token authorization (scope: reference tests/test_auth.py): accept valid tokens,
reject forged/expired/replayed, wrapper enforcement on servicers."""

import pytest

from hivemind_tpu.utils.auth import (
    AuthorizationError,
    AuthRole,
    AuthRPCWrapper,
    TokenAuthorizerBase,
)
from hivemind_tpu.utils.crypto import Ed25519PrivateKey
from hivemind_tpu.utils.serializer import MSGPackSerializer


def make_pair():
    authority = Ed25519PrivateKey()
    issuer = TokenAuthorizerBase(authority_key=authority, local_key=Ed25519PrivateKey())
    validator = TokenAuthorizerBase(local_key=Ed25519PrivateKey())
    validator.set_authority_public_key(authority.get_public_key())
    return issuer, validator


def test_token_accept_and_replay():
    issuer, validator = make_pair()
    token = issuer.issue_token()
    assert validator.validate_token(token)
    assert not validator.validate_token(token)  # replay rejected
    assert validator.validate_token(issuer.issue_token())  # fresh nonce fine


def test_token_forgery_and_expiry():
    issuer, validator = make_pair()
    imposter = TokenAuthorizerBase(authority_key=Ed25519PrivateKey())
    assert not validator.validate_token(imposter.issue_token())  # wrong authority
    assert not validator.validate_token(b"garbage")
    expired_issuer = TokenAuthorizerBase(authority_key=issuer.authority_key, token_lifetime=-120)
    assert not validator.validate_token(expired_issuer.issue_token())


def test_token_identity_binding():
    from hivemind_tpu.p2p.peer_id import PeerID

    authority = Ed25519PrivateKey()
    issuer = TokenAuthorizerBase(authority_key=authority)
    validator = TokenAuthorizerBase(local_key=Ed25519PrivateKey())
    validator.set_authority_public_key(authority.get_public_key())

    client_key = Ed25519PrivateKey()
    client_id = PeerID.from_private_key(client_key)
    other_id = PeerID.from_private_key(Ed25519PrivateKey())
    token = issuer.issue_token_for(client_key.get_public_key())
    # owner may reuse its bound token; any other identity is rejected
    assert validator.validate_token(token, sender_peer_id=client_id)
    assert validator.validate_token(token, sender_peer_id=client_id)
    assert not validator.validate_token(token, sender_peer_id=other_id)


async def test_auth_rpc_wrapper():
    from hivemind_tpu.proto import dht_pb2

    issuer, validator = make_pair()

    class Servicer:
        async def rpc_ping(self, request, context):
            return "pong"

    wrapped = AuthRPCWrapper(Servicer(), AuthRole.SERVICER, validator)
    request = dht_pb2.PingRequest(peer=dht_pb2.NodeInfo(node_id=b"x"))
    with pytest.raises(AuthorizationError):
        await wrapped.rpc_ping(request, None)

    # client wrapper stamps a token the servicer accepts
    class Stub:
        async def rpc_ping(self, request, context=None):
            return request

    client = AuthRPCWrapper(Stub(), AuthRole.CLIENT, issuer)
    stamped = await client.rpc_ping(request)
    assert stamped.peer.auth_token
    assert (await wrapped.rpc_ping(stamped, None)) == "pong"
