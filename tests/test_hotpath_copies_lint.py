"""Wires tools/check_hotpath_copies.py into the suite (ISSUE 6 satellite): a new
bytes concat or implicit-copy astype in the averaging hot path fails tier-1."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import check_hotpath_copies


def test_no_new_hotpath_copies():
    new, stale = check_hotpath_copies.check()
    assert not new, (
        "new copy/concat sites in the averaging hot path "
        "(see tools/check_hotpath_copies.py):\n" + "\n".join(new)
    )
    for entry in stale:
        print(f"note: stale hot-path allowlist entry: {entry}")
