"""Error-feedback residuals for the quantized averaging wire (ISSUE 11):
ResidualStore semantics (accumulation, reset on schema/group change, no
per-peer growth), the EF unbiasedness guarantee, and the convergence criterion
— a decentralized-SGD recipe run lossless vs 8-bit+error-feedback through the
REAL container/reducer/codec machinery reaches matched final loss."""

import asyncio
import time

import numpy as np
import pytest

from hivemind_tpu.averaging.partition import (
    TensorPartContainer,
    TensorPartReducer,
    compute_span_part_sizes,
)
from hivemind_tpu.averaging.residual import ResidualStore, compress_with_feedback
from hivemind_tpu.averaging.wire_codec import WireLink
from hivemind_tpu.compression import (
    CompressionType,
    Float16Compression,
    deserialize_tensor,
    get_codec,
    serialize_tensor,
)


# ------------------------------------------------------------------ store units


def test_store_allocates_lazily_and_views_are_writable():
    store = ResidualStore()
    store.ensure(100)
    assert store.footprint_bytes() == 0  # nothing until a lossy link touches it
    view = store.view("send", 10, 20)
    assert view.shape == (10,) and np.all(view == 0)
    view += 1.0
    assert np.all(store.view("send", 10, 20) == 1.0)  # same backing plane
    assert store.footprint_bytes() == 100 * 4


def test_store_resets_when_schema_changes():
    """'Reset on group change': a different total element count means the
    partition universe changed — stale offsets would compensate the wrong
    elements, so all residual state is discarded."""
    store = ResidualStore()
    store.ensure(64)
    store.view("send", 0, 64)[:] = 3.0
    store.ensure(64)  # same schema: state survives (group RE-composition)
    assert np.all(store.view("send", 0, 64) == 3.0)
    store.ensure(128)  # schema changed: reset
    assert store.footprint_bytes() == 0
    assert np.all(store.view("send", 0, 128) == 0)


def test_store_explicit_reset_and_no_per_peer_growth():
    """No-leak on peer departure: residual memory is exactly two planes
    (send + reduce), INDEPENDENT of how many peers come and go — there is no
    per-peer buffer to leak."""
    store = ResidualStore()
    store.ensure(256)
    for fake_peer in range(50):  # arbitrarily many groupmates over time
        store.view("send", fake_peer, fake_peer + 1)
        store.view("reduce", fake_peer, fake_peer + 1)
    assert store.footprint_bytes() == 2 * 256 * 4
    store.reset()
    assert store.footprint_bytes() == 0


def test_error_feedback_accumulation_is_unbiased():
    """The EF contract: the time-average of what crosses the wire converges to
    the true value — after R rounds the cumulative quantization error is ONE
    round's residual, not a random walk of R errors."""
    rng = np.random.RandomState(0)
    x = rng.randn(4096).astype(np.float32)
    codec = get_codec(CompressionType.UNIFORM_8BIT)
    residual = np.zeros(4096, np.float32)
    rounds = 20
    decoded_sum = np.zeros(4096, np.float64)
    single_round_err = None
    for _round in range(rounds):
        serialized = compress_with_feedback(x, codec, residual)
        decoded = deserialize_tensor(serialized)
        if single_round_err is None:
            single_round_err = float(np.abs(decoded - x).max())
        decoded_sum += decoded
    mean_err = float(np.abs(decoded_sum / rounds - x).max())
    # telescoping: mean error ~ single_round/rounds; allow generous slack
    assert mean_err < single_round_err / 3, (mean_err, single_round_err)
    # and the residual itself stays bounded (one quantization step, not R)
    assert float(np.abs(residual).max()) < 4 * single_round_err


def test_compress_with_feedback_does_not_mutate_part():
    rng = np.random.RandomState(1)
    part = rng.randn(1000).astype(np.float32)
    original = part.copy()
    residual = np.zeros(1000, np.float32)
    compress_with_feedback(part, get_codec(CompressionType.UNIFORM_8BIT), residual)
    assert np.array_equal(part, original)
    assert np.any(residual != 0)


# ------------------------------------------------------------------ wire simulation

PART_BYTES = 512


async def _wire_average(peer_vectors, tier, stores):
    """One butterfly round through the REAL TensorPartContainer /
    TensorPartReducer / codec / residual machinery, in process: every
    non-loopback part and every delta crosses the serialized wire format.
    ``tier=None`` is the lossless fp16 path; a lossy tier engages error
    feedback and the absolute-average delta leg, exactly like AllReduceRunner."""
    peers = len(peer_vectors)
    n = peer_vectors[0].size
    counts = [n // peers] * peers
    counts[-1] += n - sum(counts)
    fp16 = Float16Compression()
    link = WireLink.for_tier(tier) if tier else None
    lossy = link is not None and link.error_feedback
    containers = []
    for i in range(peers):
        peer_links = [link if j != i else None for j in range(peers)] if link else None
        containers.append(
            TensorPartContainer(
                [peer_vectors[i]], counts, compression=fp16, part_size_bytes=PART_BYTES,
                peer_links=peer_links, residuals=stores[i] if lossy else None,
            )
        )
    for owner in range(peers):
        if lossy:
            stores[owner].ensure(n)
        part_sizes = compute_span_part_sizes(counts[owner], PART_BYTES)
        reducer = TensorPartReducer([(size,) for size in part_sizes], num_senders=peers)
        arrived = {}
        for sender in range(peers):
            if sender == owner:
                arrived[sender] = containers[sender].get_raw_input_parts(owner)
            else:
                serialized = [s async for s in containers[sender].iterate_input_parts_for(owner)]
                arrived[sender] = [deserialize_tensor(s) for s in serialized]
        span_start = sum(counts[:owner])
        offset = 0
        for part_index, size in enumerate(part_sizes):
            averaged = (
                await asyncio.gather(
                    *(
                        reducer.accumulate_part(sender, part_index, arrived[sender][part_index])
                        for sender in range(peers)
                    )
                )
            )[0]
            if lossy:
                residual = stores[owner].view(
                    "reduce", span_start + offset, span_start + offset + size
                )
                payload = compress_with_feedback(averaged, link.codec, residual)
                decoded = deserialize_tensor(payload)
                for sender in range(peers):
                    if sender == owner:
                        containers[owner].register_processed_part(
                            owner, part_index, averaged - arrived[owner][part_index]
                        )
                    else:
                        containers[sender].register_processed_absolute(owner, part_index, decoded)
            else:
                for sender in range(peers):
                    delta = averaged - arrived[sender][part_index]
                    if sender == owner:
                        containers[owner].register_processed_part(owner, part_index, delta)
                    else:
                        wire_delta = deserialize_tensor(serialize_tensor(delta.copy(), fp16))
                        containers[sender].register_processed_part(owner, part_index, wire_delta)
            offset += size
    averaged_vectors = []
    for i in range(peers):
        deltas = [d async for d in containers[i].iterate_output_tensors()]
        averaged_vectors.append(peer_vectors[i] + deltas[0].reshape(-1))
    return averaged_vectors


async def test_mixed_container_lossless_parts_stay_bit_identical():
    """A container with one lossy link must serialize its LOSSLESS peers'
    parts byte-identically to the no-negotiation path."""
    rng = np.random.RandomState(3)
    tensors = [rng.randn(900).astype(np.float32)]
    counts = [300, 300, 300]
    fp16 = Float16Compression()
    store = ResidualStore()
    links = [None, WireLink.for_tier("float16"), WireLink.for_tier("uniform8")]
    container = TensorPartContainer(
        [tensors[0].copy()], counts, compression=fp16, part_size_bytes=PART_BYTES,
        peer_links=links, residuals=store,
    )
    baseline = TensorPartContainer(
        [tensors[0].copy()], counts, compression=fp16, part_size_bytes=PART_BYTES
    )
    for peer_index in (0, 1):  # None-link and explicit float16 link
        got = [s async for s in container.iterate_input_parts_for(peer_index)]
        expected = [s async for s in baseline.iterate_input_parts_for(peer_index)]
        assert [g.SerializeToString() for g in got] == [e.SerializeToString() for e in expected]
    # the lossy peer's parts decode within quantization tolerance, with EF armed
    lossy_parts = [s async for s in container.iterate_input_parts_for(2)]
    decoded = np.concatenate([deserialize_tensor(s) for s in lossy_parts])
    assert np.abs(decoded - tensors[0][600:]).max() < 0.2
    assert store.footprint_bytes() > 0


async def test_quantized_round_matches_lossless_within_tolerance():
    rng = np.random.RandomState(7)
    peer_vectors = [rng.randn(1000).astype(np.float32) for _ in range(3)]
    true_average = np.mean(peer_vectors, axis=0)
    stores = [ResidualStore() for _ in range(3)]
    quantized = await _wire_average([v.copy() for v in peer_vectors], "uniform8", stores)
    for result in quantized:
        assert np.abs(result - true_average).max() < 0.05
    # the quantized all-gather leg is near-consensus: peers disagree only by
    # the span owner's unquantized advantage plus fp32 rounding, never by an
    # accumulated drift
    assert np.abs(quantized[0] - quantized[1]).max() < 0.05


async def test_convergence_quantized_with_feedback_matches_lossless():
    """The ISSUE 11 convergence criterion: a tiny decentralized-SGD recipe
    (least squares, gradients averaged through the wire every step) reaches the
    same final loss with 8-bit+error-feedback as with the lossless tier."""
    peers, dim, samples, steps, lr = 2, 24, 48, 30, 0.15
    rng = np.random.RandomState(11)
    data = [
        (rng.randn(samples, dim).astype(np.float32),
         rng.randn(samples).astype(np.float32))
        for _ in range(peers)
    ]

    def global_loss(w):
        return float(
            np.mean([np.mean((a @ w - b) ** 2) for a, b in data])
        )

    async def train(tier):
        stores = [ResidualStore() for _ in range(peers)]
        weights = [np.zeros(dim, np.float32) for _ in range(peers)]
        for _step in range(steps):
            grads = [
                (2.0 / samples) * (a.T @ (a @ w - b))
                for (a, b), w in zip(data, weights)
            ]
            averaged = await _wire_average(
                [g.astype(np.float32) for g in grads], tier, stores
            )
            weights = [
                (w - lr * g).astype(np.float32) for w, g in zip(weights, averaged)
            ]
        return global_loss(weights[0])

    lossless = await train(None)
    quantized = await train("uniform8")
    assert quantized == pytest.approx(lossless, rel=0.02), (lossless, quantized)


# ------------------------------------------------------------------ quantile runtime


def test_quantile_compress_runtime_is_bounded():
    """ISSUE 11 satellite: Quantile8BitQuantization estimates its codebook from
    a bounded hash sample — a multi-M-element tensor must never pay a full-array
    sort/np.quantile on the codec path. Regression bound: 16M elements well
    under 2.5 s (the sampled path measures ~0.6 s on this host; a full-sort or
    per-quantile implementation blows past the bound many times over)."""
    codec = get_codec(CompressionType.QUANTILE_8BIT)
    x = np.random.RandomState(0).randn(16_000_000).astype(np.float32)
    started = time.perf_counter()
    serialized = codec.compress(x)
    elapsed = time.perf_counter() - started
    assert elapsed < 2.5, f"quantile compress took {elapsed:.2f}s for 16M elements"
    decoded = deserialize_tensor(serialized)
    # sanity: the bounded sample still yields a usable codebook
    assert float(np.abs(decoded - x).mean()) < 0.05
