"""Wire-equivalence and bit-identity guarantees for the zero-copy averaging data
path (ISSUE 6): the view-based ``TensorPartContainer`` must serialize byte-identical
parts to the old concat-everything implementation for every codec, the in-place
``TensorPartReducer`` must produce bit-identical averages, and a real two-peer
all-reduce must match an op-by-op numpy replay of the wire pipeline exactly."""

import asyncio
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from hivemind_tpu.averaging.allreduce import AllReduceRunner, AveragingMode
from hivemind_tpu.averaging.partition import (
    TensorPartContainer,
    TensorPartReducer,
    compute_span_part_sizes,
)
from hivemind_tpu.compression import (
    CompressionType,
    deserialize_tensor,
    get_codec,
    serialize_tensor,
)
from hivemind_tpu.proto import runtime_pb2

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ALL_CODECS = sorted(runtime_pb2.CompressionType.values())


def _equivalence_tensors():
    """Mixed shapes/dtypes, with values beyond the fp16 range so the FLOAT16 clip
    path is exercised (an unclipped in-place bug would change bytes here)."""
    rng = np.random.RandomState(7)
    return [
        rng.randn(1111).astype(np.float32) * 1e5,  # exceeds FP16_MAX: clip must fire
        rng.randn(64, 32).astype(np.float32),
        rng.randn(501).astype(np.float64),  # conversion-copy (private) path
        rng.randn(3, 5, 7).astype(np.float32),
    ]


@pytest.mark.parametrize("compression_type", ALL_CODECS)
async def test_wire_equivalence_every_codec(compression_type):
    """Container-serialized parts must be byte-identical to serializing slices of
    the naive concatenated fp32 stream — across part boundaries that straddle
    tensors, for every registered codec."""
    codec = get_codec(compression_type)
    tensors = _equivalence_tensors()
    originals = [t.copy() for t in tensors]
    total = sum(t.size for t in tensors)
    counts = [total // 3, total // 5, total - total // 3 - total // 5]
    part_size_bytes = 1024  # small parts: many boundary-straddling cases

    # the reference construction the refactor replaced: one concatenated fp32 flat
    flat = np.concatenate([t.reshape(-1).astype(np.float32) for t in tensors])
    expected_spans = []
    offset = 0
    for count in counts:
        for size in compute_span_part_sizes(count, part_size_bytes):
            expected_spans.append((offset, offset + size))
            offset += size

    container = TensorPartContainer(tensors, counts, compression=codec, part_size_bytes=part_size_bytes)
    produced = []
    for peer_index in range(len(counts)):
        async for serialized in container.iterate_input_parts_for(peer_index):
            produced.append(serialized)

    assert len(produced) == len(expected_spans)
    for (start, stop), actual in zip(expected_spans, produced):
        expected = serialize_tensor(flat[start:stop].copy(), codec)
        assert actual.SerializeToString() == expected.SerializeToString(), (
            f"codec {compression_type}: part [{start}:{stop}) bytes diverged"
        )
    # in-place compression must never have leaked into caller-owned tensors
    for tensor, original in zip(tensors, originals):
        assert np.array_equal(tensor, original), "container mutated an input tensor"


async def test_reducer_in_place_average_bit_identical():
    """np.add/np.multiply/np.divide with out= must reproduce the naive
    ``(acc + p*w) / total`` bit for bit, including the weighted path."""
    rng = np.random.RandomState(3)
    parts = [rng.randn(1000).astype(np.float32) for _ in range(3)]
    weights = [0.3, 1.0, 2.5]

    reducer = TensorPartReducer([(1000,)], num_senders=3)
    results = await asyncio.gather(
        *(reducer.accumulate_part(i, 0, parts[i], weight=weights[i]) for i in range(3))
    )
    naive = np.zeros(1000, np.float32)
    for part, weight in zip(parts, weights):
        naive += part * weight
    naive = naive / sum(weights)
    for result in results:
        assert np.array_equal(result, naive), "in-place reduction diverged bitwise"


async def test_reducer_late_part_cannot_corrupt_resolved_average():
    """The accumulator IS the result after the in-place divide: a laggard whose
    part arrives after resolution (its denominator already shrunk) must not
    mutate the average other senders already received."""
    reducer = TensorPartReducer([(4,)], num_senders=2)
    early = asyncio.create_task(reducer.accumulate_part(0, 0, np.full(4, 2.0, np.float32)))
    await asyncio.sleep(0.01)
    reducer.on_sender_failed(1)
    resolved = await asyncio.wait_for(early, timeout=2)
    assert np.array_equal(resolved, np.full(4, 2.0, np.float32))
    snapshot = resolved.copy()
    late = await reducer.accumulate_part(1, 0, np.full(4, 99.0, np.float32))
    assert np.array_equal(late, snapshot), "late part mutated the resolved average"
    assert np.array_equal(resolved, snapshot)


async def test_prefetch_knob_is_wired():
    """ISSUE 6 satellite: the container's prefetch arg used to be accepted and
    dropped (iterate_input_parts_for hardcoded 4); it must be stored and the
    runner must plumb its own prefetch through."""
    tensors = [np.zeros(64, np.float32)]
    container = TensorPartContainer(tensors, [64], prefetch=2)
    assert container.prefetch == 2
    with pytest.raises(AssertionError):
        TensorPartContainer(tensors, [64], prefetch=0)


def _replay_two_peer_allreduce(flats, counts, codec_type, part_size_bytes):
    """Op-by-op numpy replay of the two-peer wire pipeline: what each peer's
    per-part deltas must be, bit for bit."""
    codec = get_codec(codec_type)

    def wire_roundtrip(part):
        return deserialize_tensor(serialize_tensor(part.copy(), codec))

    deltas = [np.empty_like(flats[0]) for _ in range(2)]
    offset = 0
    for owner, count in enumerate(counts):
        for size in compute_span_part_sizes(count, part_size_bytes):
            start, stop = offset, offset + size
            local = flats[owner][start:stop]            # loopback: raw fp32
            remote_sender = 1 - owner
            remote = wire_roundtrip(flats[remote_sender][start:stop])  # via the wire
            acc = np.zeros(size, np.float32)
            acc += local  # 2 senders: fp32 addition is commutative, order-free
            acc += remote
            averaged = acc / 2.0
            deltas[owner][start:stop] = averaged - local
            # the delta to the remote sender rides the wire (and is codec-rounded)
            deltas[remote_sender][start:stop] = wire_roundtrip(averaged - remote)
            offset = stop
    return deltas


@pytest.mark.parametrize("codec_type", [CompressionType.NONE, CompressionType.FLOAT16])
async def test_two_peer_allreduce_bit_identical_to_replay(codec_type):
    """A real two-peer all-reduce over localhost transport produces deltas that
    match the numpy replay of the exact wire pipeline — no copies, reorderings,
    or in-place tricks may perturb a single bit."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_allreduce import _AllreduceHarness

    part_size_bytes = 600  # several parts per span
    rng = np.random.RandomState(11)
    n = 800
    flats = [rng.randn(n).astype(np.float32) * 3.0 for _ in range(2)]
    counts = [n // 2, n - n // 2]
    codec = get_codec(codec_type)

    from hivemind_tpu.p2p import P2P

    p2ps = [await P2P.create() for _ in range(2)]
    await p2ps[1].connect(p2ps[0].get_visible_maddrs()[0])
    harnesses = [_AllreduceHarness(p) for p in p2ps]
    for harness in harnesses:
        await harness.register()
    try:
        runners = []
        for i in range(2):
            runner = AllReduceRunner(
                p2p=p2ps[i],
                group_id=b"equivalence-group",
                tensors=[flats[i].copy()],
                ordered_peer_ids=[p.peer_id for p in p2ps],
                peer_element_counts=counts,
                modes=[AveragingMode.NODE, AveragingMode.NODE],
                get_stub=harnesses[i].get_stub,
                compression=codec,
                part_size_bytes=part_size_bytes,
                sender_timeout=10.0,
                reducer_timeout=20.0,
            )
            harnesses[i].runner = runner
            runners.append(runner)

        async def run_one(i):
            return [d async for d in runners[i].run()]

        all_deltas = await asyncio.gather(*(run_one(i) for i in range(2)))
    finally:
        for p2p in p2ps:
            await p2p.shutdown()

    expected = _replay_two_peer_allreduce(flats, counts, codec_type, part_size_bytes)
    for i in range(2):
        got = all_deltas[i][0].reshape(-1)
        assert np.array_equal(got, expected[i]), (
            f"peer {i} deltas diverged from the wire replay (codec {codec_type}); "
            f"max abs diff {np.max(np.abs(got - expected[i]))}"
        )


async def _run_runner_group(flats, counts, links_by_peer, part_size_bytes=600):
    """A real localhost all-reduce with hand-built per-peer link maps
    (ISSUE 11); returns each peer's resulting vector."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_allreduce import _AllreduceHarness

    from hivemind_tpu.averaging.residual import ResidualStore
    from hivemind_tpu.compression import Float16Compression
    from hivemind_tpu.p2p import P2P

    n_peers = len(flats)
    p2ps = [await P2P.create() for _ in range(n_peers)]
    for i, p2p in enumerate(p2ps):
        for other in p2ps[:i]:
            await p2p.connect(other.get_visible_maddrs()[0])
    harnesses = [_AllreduceHarness(p) for p in p2ps]
    for harness in harnesses:
        await harness.register()
    try:
        runners = []
        for i in range(n_peers):
            runner = AllReduceRunner(
                p2p=p2ps[i],
                group_id=b"tier-interop-group",
                tensors=[flats[i].copy()],
                ordered_peer_ids=[p.peer_id for p in p2ps],
                peer_element_counts=counts,
                modes=[AveragingMode.NODE] * n_peers,
                get_stub=harnesses[i].get_stub,
                compression=Float16Compression(),
                part_size_bytes=part_size_bytes,
                sender_timeout=10.0,
                reducer_timeout=20.0,
                links=links_by_peer[i],
                residuals=ResidualStore(),
            )
            harnesses[i].runner = runner
            runners.append(runner)

        async def run_one(i):
            return [d async for d in runners[i].run()]

        all_deltas = await asyncio.gather(*(run_one(i) for i in range(n_peers)))
    finally:
        for p2p in p2ps:
            await p2p.shutdown()
    return [flats[i] + all_deltas[i][0].reshape(-1) for i in range(n_peers)]


async def test_two_peer_quantized_allreduce_within_tolerance():
    """The lossy-tier analog of the bit-identity replay: a uniform8 link with
    error feedback (absolute_part delta leg) lands every peer within
    quantization distance of the true average."""
    from hivemind_tpu.averaging.wire_codec import WireLink

    rng = np.random.RandomState(13)
    n = 4000
    flats = [rng.randn(n).astype(np.float32) for _ in range(2)]
    counts = [n // 2, n - n // 2]
    q8 = WireLink.for_tier("uniform8")
    results = await _run_runner_group(flats, counts, [{1: q8}, {0: q8}])
    true_average = (flats[0] + flats[1]) / 2
    for i, result in enumerate(results):
        assert np.abs(result - true_average).max() < 0.05, f"peer {i} diverged"


async def test_mixed_tier_group_interop():
    """ISSUE 11 satellite: an 8-bit peer in an fp16 group reduces correctly —
    one link runs uniform8 (both directions, with EF) while the other two stay
    float16; every peer still lands on the average within tolerance."""
    from hivemind_tpu.averaging.wire_codec import WireLink

    rng = np.random.RandomState(17)
    n = 3000
    flats = [rng.randn(n).astype(np.float32) for _ in range(3)]
    counts = [1000, 1000, 1000]
    q8, fp16 = WireLink.for_tier("uniform8"), WireLink.for_tier("float16")
    # peer 2 is the "slow WAN" peer: its links run 8-bit; peers 0<->1 stay fp16
    links_by_peer = [
        {1: fp16, 2: q8},
        {0: fp16, 2: q8},
        {0: q8, 1: q8},
    ]
    results = await _run_runner_group(flats, counts, links_by_peer)
    true_average = np.mean(flats, axis=0)
    for i, result in enumerate(results):
        assert np.abs(result - true_average).max() < 0.05, f"peer {i} diverged"
    # the fp16-only link kept its classic delta path: peers 0 and 1 agree on
    # each other's spans to fp16 precision
    assert np.abs(results[0][:2000] - results[1][:2000]).max() < 2e-3


def test_benchmark_averaging_smoke():
    """The throughput path end-to-end (DHT + matchmaking + butterfly all-reduce in
    subprocesses): --smoke must succeed on every step, so a data-path regression
    fails tier-1 loudly instead of only showing up in nightly benchmarks."""
    script = os.path.join(REPO_ROOT, "benchmarks", "benchmark_averaging.py")
    run = subprocess.run(
        [sys.executable, script, "--smoke"],
        timeout=180,
        capture_output=True,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert run.returncode == 0, f"smoke benchmark failed:\n{run.stdout[-2000:]}\n{run.stderr[-2000:]}"
    payload = next(line for line in run.stdout.splitlines() if line.startswith("{"))
    result = json.loads(payload)
    assert result["extra"]["success_rate"] == 1.0
    assert result["metric"] == "averaging_gbps_per_peer"
