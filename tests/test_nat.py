"""NAT traversal: relay whoami (observed endpoint), AutoNAT-style dial-back probe,
and DCUtR-style hole punching upgrading a relayed connection to a direct one
(scope: reference p2p_daemon.py:84-147 AutoNAT/AutoRelay/DCUtR flags)."""

import asyncio
import subprocess
from pathlib import Path

import pytest

from hivemind_tpu.p2p import NATTraversal, P2P, P2PContext
from hivemind_tpu.p2p.relay import RelayClient
from hivemind_tpu.proto import test_pb2

NATIVE_DIR = Path(__file__).parent.parent / "hivemind_tpu" / "native"
RELAY_BIN = NATIVE_DIR / "relay_daemon"


@pytest.fixture(scope="module")
def relay_process():
    if not RELAY_BIN.exists():
        subprocess.run(["make"], cwd=NATIVE_DIR, check=True, capture_output=True)
    proc = subprocess.Popen([str(RELAY_BIN), "0"], stdout=subprocess.PIPE, text=True)
    port = int(proc.stdout.readline().strip().rsplit(" ", 1)[-1])
    yield port
    proc.kill()
    proc.wait()


async def test_relay_whoami(relay_process):
    p2p = await P2P.create()
    try:
        relay = RelayClient(p2p, "127.0.0.1", relay_process)
        host, port = await relay.whoami()
        assert host == "127.0.0.1" and 0 < port < 65536
    finally:
        await p2p.shutdown()


async def test_reachability_probe():
    alice = await P2P.create()
    bob = await P2P.create()
    try:
        await NATTraversal(bob).register_handlers()
        await alice.connect(bob.get_visible_maddrs()[0])
        nat_alice = NATTraversal(alice)
        # our real listener is reachable from bob
        reachable = await nat_alice.check_reachability(bob.peer_id)
        assert [str(m) for m in alice.get_visible_maddrs()] == reachable
        # a dead port is correctly reported unreachable
        dead = f"/ip4/127.0.0.1/tcp/1/p2p/{alice.peer_id.to_base58()}"
        reachable = await nat_alice.check_reachability(
            bob.peer_id, maddrs=[alice.get_visible_maddrs()[0], dead]
        )
        assert dead not in reachable and len(reachable) == 1
    finally:
        await alice.shutdown()
        await bob.shutdown()


async def test_hole_punch_upgrades_relayed_connection(relay_process):
    """Two peers talk only through the relay; hole punching swaps in a direct
    connection that keeps serving RPCs."""
    server = await P2P.create()
    client = await P2P.create()
    try:
        async def double(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
            return test_pb2.TestResponse(number=request.number * 2)

        await server.add_protobuf_handler("double", double, test_pb2.TestRequest)
        await NATTraversal(server).register_handlers()
        nat_client = NATTraversal(client)
        await nat_client.register_handlers()

        server_relay = await RelayClient.create(server, "127.0.0.1", relay_process)
        client_relay = RelayClient(client, "127.0.0.1", relay_process)
        await client_relay.dial(server.peer_id)
        relayed_conn = client._connections[server.peer_id]
        response = await client.call_protobuf_handler(
            server.peer_id, "double", test_pb2.TestRequest(number=5), test_pb2.TestResponse
        )
        assert response.number == 10

        # punch: both sides dial direct; the map entry must change connections
        assert await nat_client.hole_punch(server.peer_id)
        await asyncio.sleep(0.2)
        direct_conn = client._connections[server.peer_id]
        assert direct_conn is not relayed_conn and not direct_conn.is_closed
        response = await client.call_protobuf_handler(
            server.peer_id, "double", test_pb2.TestRequest(number=8), test_pb2.TestResponse
        )
        assert response.number == 16
        await server_relay.close()
    finally:
        await client.shutdown()
        await server.shutdown()
