"""End-to-end training with remote experts in the model (scope: reference
tests/test_training.py — a model whose middle layer is a RemoteExpert trains through
the RPC boundary with the collaborative Optimizer; the server-side expert trains
itself on every backward call)."""

import time

import numpy as np
import optax

import jax
import jax.numpy as jnp

from hivemind_tpu.dht import DHT
from hivemind_tpu.moe import ExpertInfo, RemoteExpert, background_server
from hivemind_tpu.optim import Optimizer

HID = 16


def test_training_through_remote_expert():
    with background_server(
        expert_uids=["train_ffn.0"], expert_cls="ffn", hidden_dim=HID,
        optim_factory=lambda: optax.adam(1e-3),
    ) as (server_dht, server):
        client_dht = DHT(initial_peers=[str(m) for m in server_dht.get_visible_maddrs()], start=True)
        opt = None
        try:
            time.sleep(0.5)
            expert = RemoteExpert(ExpertInfo("train_ffn.0", server_dht.peer_id), client_dht.node.p2p)

            rng = np.random.RandomState(0)
            features = rng.randn(128, HID).astype(np.float32)
            true_w = rng.randn(HID).astype(np.float32)
            targets = features @ true_w

            params = {
                "w_in": jnp.asarray(rng.randn(HID, HID) * 0.3, jnp.float32),
                "w_out": jnp.asarray(rng.randn(HID) * 0.3, jnp.float32),
            }

            def loss_fn(p, x, y):
                hidden = jnp.tanh(x @ p["w_in"])
                hidden = expert(hidden)  # RPC in the middle of the model
                prediction = hidden @ p["w_out"]
                return jnp.mean((prediction - y) ** 2)

            loss_and_grad = jax.value_and_grad(loss_fn)
            opt = Optimizer(
                dht=client_dht, run_id="train_e2e", target_batch_size=16,
                params=params, optimizer=optax.adam(2e-2), batch_size_per_step=16,
                matchmaking_time=1.0,
                tracker_opts=dict(min_refresh_period=0.2, default_refresh_period=0.3),
            )
            first_loss = last_loss = None
            for step in range(30):
                idx = rng.choice(len(features), 16)
                loss, grads = loss_and_grad(opt.params, features[idx], targets[idx])
                first_loss = first_loss if first_loss is not None else float(loss)
                last_loss = float(loss)
                opt.step(grads)
                time.sleep(0.1)
            assert last_loss < first_loss / 2, (first_loss, last_loss)
            assert opt.local_epoch >= 2  # epochs advanced (single-peer local grads)
            # the server-side expert trained too: one update per backward RPC
            assert server.backends["train_ffn.0"].update_count >= 10
        finally:
            if opt is not None:
                opt.shutdown()
            client_dht.shutdown()
