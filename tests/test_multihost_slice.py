"""Multi-host slice semantics (VERDICT r2 next-round #4): TWO REAL
`jax.distributed`-initialized CPU processes form ONE mesh, and the slice joins a
swarm as ONE peer — only process 0 owns any networking; process 1 participates in
collective staging/adoption and provably never constructs a DHT.

The worker script below is executed in two subprocesses (4 virtual devices each →
one 8-device dp mesh). Process 0 also hosts a plain host-resident peer so the
swarm has two members; after the round BOTH processes must hold the exact
cross-peer average in their device shards.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
proc_id = int(sys.argv[1])
port = sys.argv[2]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=proc_id
)
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hivemind_tpu.averaging import DecentralizedAverager, SliceAverager
from hivemind_tpu.dht import DHT

devices = np.array(jax.devices()).reshape(8)
mesh = Mesh(devices, ("dp",))

rng = np.random.RandomState(7)
w_host = rng.randn(8, 16).astype(np.float32)
b_host = rng.randn(32).astype(np.float32)
tree = {
    "w": jax.device_put(w_host, NamedSharding(mesh, P("dp"))),
    "b": jax.device_put(b_host, NamedSharding(mesh, P())),
}
peer_w = rng.randn(8, 16).astype(np.float32)  # same RNG on both procs: same values
peer_b = rng.randn(32).astype(np.float32)

common = dict(
    prefix="slice_round", start=True, target_group_size=2,
    min_matchmaking_time=1.0, request_timeout=1.0,
    sender_timeout=5.0, reducer_timeout=10.0,
)

plain_dht = plain_peer = None
if proc_id == 0:
    boot = DHT(start=True)
    maddrs = [str(m) for m in boot.get_visible_maddrs()]
    plain_dht = DHT(initial_peers=maddrs, start=True)
    # flatten order is sorted dict keys: b, w
    plain_peer = DecentralizedAverager([peer_b, peer_w], plain_dht, **common)
    dht_factory = lambda: boot
else:
    dht_factory = lambda: (_ for _ in ()).throw(
        AssertionError("dht_factory called on a non-network process")
    )

slice_avg = SliceAverager(tree, mesh, dht_factory, **(common if proc_id == 0 else {}))

# the structural claim: non-zero processes own NO networking objects at all
if proc_id != 0:
    assert slice_avg.dht is None and slice_avg.averager is None
    assert not slice_avg.is_network_process

if proc_id == 0:
    control = plain_peer.step(wait=False, timeout=40)
    ok = slice_avg.step(timeout=40)
    assert control.result(timeout=60) is not None
else:
    ok = slice_avg.step(timeout=40)

assert ok, f"[{proc_id}] slice round failed"
expected_w = (w_host + peer_w) / 2.0
expected_b = (b_host + peer_b) / 2.0
averaged = slice_avg.device_tree


def check_shards(arr, expected):
    # a multi-process global array cannot be materialized whole; every process
    # verifies the shards IT holds — together the two processes cover the array
    assert arr.addressable_shards, "process holds no shards"
    for shard in arr.addressable_shards:
        np.testing.assert_allclose(
            np.asarray(shard.data), expected[shard.index], rtol=1e-6, atol=1e-7
        )


check_shards(averaged["w"], expected_w)
check_shards(averaged["b"], expected_b)
assert averaged["w"].sharding.spec == P("dp")
if proc_id == 0:
    with plain_peer.get_tensors() as tensors:
        np.testing.assert_allclose(tensors[0], expected_b, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(tensors[1], expected_w, rtol=1e-6, atol=1e-7)
    plain_peer.shutdown(); plain_dht.shutdown()

# ---- failure path: the other swarm peer is gone, so the network round cannot
# form a group; EVERY process must observe ok=False and device state unchanged
ok_fail = slice_avg.step(timeout=6)
assert not ok_fail, f"[{proc_id}] round unexpectedly succeeded with no peers"
check_shards(slice_avg.device_tree["w"], expected_w)
check_shards(slice_avg.device_tree["b"], expected_b)

slice_avg.shutdown()
print(f"SLICE_OK_{proc_id}", flush=True)
"""


def test_two_process_slice_is_one_swarm_peer(tmp_path):
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = str(probe.getsockname()[1])
    script = tmp_path / "slice_worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ, PYTHONPATH=os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if p]
    ))
    workers = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for i in range(2)
    ]
    outputs = []
    try:
        for i, worker in enumerate(workers):
            out, _ = worker.communicate(timeout=420)
            outputs.append(out)
            assert worker.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
            assert f"SLICE_OK_{i}" in out, out[-3000:]
    finally:
        for worker in workers:
            if worker.poll() is None:
                worker.kill()
