"""Collaborative optimizer: grad averager semantics, progress tracker aggregation,
state averager optax updates, and full multi-peer convergence on a toy task
(scope: reference tests/test_optimizer.py)."""

import threading
import time

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from hivemind_tpu.dht import DHT
from hivemind_tpu.optim import GradientAverager, Optimizer, ProgressTracker, TrainingStateAverager
from hivemind_tpu.utils.timed_storage import get_dht_time

from swarm_utils import launch_dht_swarm


def test_grad_averager_accumulation():
    dhts = launch_dht_swarm(2)
    try:
        like = [np.zeros(10, np.float32)]
        averagers = [
            GradientAverager(like, dht=dht, prefix="gradacc", start=True,
                             target_group_size=2, min_matchmaking_time=1.0)
            for dht in dhts
        ]
        # peer 0: two microbatches of 4; peer 1: one microbatch of 12
        averagers[0].accumulate_grads_([np.full(10, 1.0, np.float32)], batch_size=4)
        averagers[0].accumulate_grads_([np.full(10, 2.0, np.float32)], batch_size=4)
        averagers[1].accumulate_grads_([np.full(10, 5.0, np.float32)], batch_size=12)
        assert averagers[0].local_samples_accumulated == 8
        controls = [a.step(wait=False, timeout=30) for a in averagers]
        for c in controls:
            c.result(timeout=60)
        # per-peer normalized grads: p0 = (1*4+2*4)/8 = 1.5 with weight 8;
        # p1 = 5*12/12 = 5 with weight 12 -> weighted mean = (1.5*8 + 5*12)/20 = 3.6
        for averager in averagers:
            with averager.use_averaged_gradients() as grads:
                assert np.allclose(grads[0], 3.6, atol=1e-4)
        # accumulators were reset by step
        assert all(a.local_samples_accumulated == 0 for a in averagers)
        for a in averagers:
            a.shutdown()
    finally:
        for dht in dhts:
            dht.shutdown()


def test_progress_tracker_aggregation():
    dhts = launch_dht_swarm(2)
    try:
        trackers = [
            ProgressTracker(dht, "trackrun", target_batch_size=100, min_refresh_period=0.2,
                            default_refresh_period=0.5)
            for dht in dhts
        ]
        trackers[0].report_local_progress(0, 30)
        trackers[1].report_local_progress(0, 30)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if all(t.global_progress.samples_accumulated >= 60 for t in trackers):
                break
            time.sleep(0.3)
        for tracker in trackers:
            assert tracker.global_progress.samples_accumulated >= 60
            assert tracker.global_progress.num_peers == 2
            assert not tracker.ready_to_update_epoch or tracker.global_progress.eta_next_epoch <= get_dht_time()
        # crossing the target flips readiness
        trackers[0].report_local_progress(0, 80)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not trackers[1].ready_to_update_epoch:
            time.sleep(0.3)
        assert trackers[1].ready_to_update_epoch
        # epoch update resets global accounting
        for tracker in trackers:
            tracker.update_epoch(1)
        assert all(t.global_epoch == 1 for t in trackers)
        for tracker in trackers:
            tracker.shutdown()
    finally:
        for dht in dhts:
            dht.shutdown()


def test_state_averager_optax_roundtrip():
    dht = DHT(start=True)
    try:
        params = {"w": jnp.ones((4, 2)), "b": jnp.zeros(2)}
        averager = TrainingStateAverager(
            dht=dht, optimizer=optax.sgd(0.5), params=params, prefix="statetest", start=True,
        )
        grads = {"w": jnp.full((4, 2), 0.2), "b": jnp.full(2, 0.4)}
        averager.apply_optimizer_step(grads)
        new_params = averager.params
        assert np.allclose(new_params["w"], 1.0 - 0.5 * 0.2, atol=1e-6)
        assert np.allclose(new_params["b"], -0.5 * 0.4, atol=1e-6)
        # host staging round trip preserves values
        host = averager._host_state_tensors()
        averager._load_host_state_tensors(host)
        assert np.allclose(averager.params["w"], new_params["w"], atol=1e-6)
        averager.shutdown()
    finally:
        dht.shutdown()


def test_optimizer_collaborative_convergence():
    """Two peers jointly minimize a least-squares objective; epochs must stay in sync
    and the loss must drop by >10x (the shape of reference benchmark_optimizer.py)."""
    rng = np.random.RandomState(0)
    true_w = rng.randn(8).astype(np.float32)
    features = rng.randn(256, 8).astype(np.float32)
    targets = features @ true_w

    def make_loss_fn():
        @jax.jit
        def loss_and_grad(params, x, y):
            def loss_fn(p):
                pred = x @ p["w"]
                return jnp.mean((pred - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            return loss, grads

        return loss_and_grad

    dhts = launch_dht_swarm(2)
    results = {}
    errors = []

    def run_peer(index: int, dht: DHT):
        try:
            params = {"w": jnp.zeros(8, jnp.float32)}
            opt = Optimizer(
                dht=dht, run_id="convergence_test", target_batch_size=64,
                params=params, optimizer=optax.sgd(0.3),
                batch_size_per_step=16, matchmaking_time=1.5, averaging_timeout=30,
                average_state_every=1, target_group_size=2, verbose=False,
                tracker_opts=dict(min_refresh_period=0.3, default_refresh_period=0.5),
            )
            loss_and_grad = make_loss_fn()
            rng_local = np.random.RandomState(index)
            first_loss = last_loss = None
            for step in range(60):
                if opt.local_epoch >= 5:
                    break
                idx = rng_local.choice(len(features), 16)
                loss, grads = loss_and_grad(opt.params, features[idx], targets[idx])
                if first_loss is None:
                    first_loss = float(loss)
                last_loss = float(loss)
                opt.step(grads)
                # pace the loop like real compute: progress records must have time to
                # propagate, or each peer would finish whole epochs solo
                time.sleep(0.25)
            results[index] = (first_loss, last_loss, opt.local_epoch, np.asarray(opt.params["w"]))
            opt.shutdown()
        except Exception as e:
            import traceback

            errors.append((index, e, traceback.format_exc()))

    threads = [threading.Thread(target=run_peer, args=(i, dht)) for i, dht in enumerate(dhts)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    try:
        assert not errors, f"peer failures: {errors}"
        assert len(results) == 2
        for index, (first_loss, last_loss, epoch, w) in results.items():
            assert epoch >= 2, f"peer {index} stuck at epoch {epoch}"
            assert last_loss < first_loss / 10, (
                f"peer {index}: loss {first_loss:.4f} -> {last_loss:.4f} did not converge"
            )
        # state averaging keeps peers' parameters in sync
        w0, w1 = results[0][3], results[1][3]
        assert np.allclose(w0, w1, atol=0.05), f"peers diverged: {np.abs(w0 - w1).max()}"
    finally:
        for dht in dhts:
            dht.shutdown()


@pytest.mark.slow  # ~60 s (three-peer convergence); client-mode averaging
# semantics stay covered in ~1 s by test_averaging.py::test_averaging_client_mode
def test_optimizer_client_mode_peer_contributes():
    """A client_mode peer (firewalled: sends gradients, never reduces) trains
    alongside two full peers; all three stay epoch-synced and converge, and the
    client's samples count toward the global batch (reference optimizer.py
    client_mode semantics)."""
    rng = np.random.RandomState(1)
    true_w = rng.randn(8).astype(np.float32)
    features = rng.randn(256, 8).astype(np.float32)
    targets = features @ true_w

    @jax.jit
    def loss_and_grad(params, x, y):
        def loss_fn(p):
            return jnp.mean((x @ p["w"] - y) ** 2)

        return jax.value_and_grad(loss_fn)(params)

    dhts = launch_dht_swarm(3)
    results = {}
    errors = []

    def run_peer(index: int, dht: DHT, client_mode: bool):
        try:
            opt = Optimizer(
                dht=dht, run_id="client_mode_test", target_batch_size=96,
                params={"w": jnp.zeros(8, jnp.float32)}, optimizer=optax.sgd(0.3),
                batch_size_per_step=16, matchmaking_time=1.5, averaging_timeout=30,
                average_state_every=1, target_group_size=2, client_mode=client_mode,
                verbose=False,
                tracker_opts=dict(min_refresh_period=0.3, default_refresh_period=0.5),
            )
            rng_local = np.random.RandomState(index)
            first_loss = last_loss = None
            for _ in range(60):
                if opt.local_epoch >= 4:
                    break
                idx = rng_local.choice(len(features), 16)
                loss, grads = loss_and_grad(opt.params, features[idx], targets[idx])
                first_loss = first_loss if first_loss is not None else float(loss)
                last_loss = float(loss)
                opt.step(grads)
                time.sleep(0.25)
            results[index] = (first_loss, last_loss, opt.local_epoch, client_mode)
            opt.shutdown()
        except Exception as e:
            import traceback

            errors.append((index, e, traceback.format_exc()))

    threads = [
        threading.Thread(target=run_peer, args=(i, dht, i == 2))
        for i, dht in enumerate(dhts)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    try:
        assert not errors, f"peer failures: {errors}"
        assert len(results) == 3
        for index, (first_loss, last_loss, epoch, client_mode) in results.items():
            role = "client" if client_mode else "node"
            assert epoch >= 2, f"{role} peer {index} stuck at epoch {epoch}"
            assert last_loss < first_loss / 5, (
                f"{role} peer {index}: loss {first_loss:.4f} -> {last_loss:.4f}"
            )
    finally:
        for dht in dhts:
            dht.shutdown()


def test_averager_rejects_mismatched_schema():
    """Averaging only makes sense over identical tensor schemas: peers whose tensor
    shapes differ must never form a group (the reference guards this with a schema
    hash checked at rpc_join_group — averager.py:812-821)."""
    from hivemind_tpu.averaging import DecentralizedAverager

    dhts = launch_dht_swarm(2)
    try:
        avg_a = DecentralizedAverager(
            [np.zeros((4, 4), np.float32)], dhts[0], prefix="schema_guard",
            start=True, min_matchmaking_time=1.0, request_timeout=1.0,
        )
        avg_b = DecentralizedAverager(
            [np.zeros((8,), np.float32)], dhts[1], prefix="schema_guard",
            start=True, min_matchmaking_time=1.0, request_timeout=1.0,
        )
        assert avg_a.schema_hash != avg_b.schema_hash
        # both step concurrently under the same prefix; neither may accept the other
        control_b = avg_b.step(wait=False, timeout=6.0, allow_retries=False)
        with pytest.raises(Exception):
            avg_a.step(timeout=6.0, allow_retries=False)
        with pytest.raises(Exception):
            control_b.result(timeout=15)
        avg_a.shutdown()
        avg_b.shutdown()
    finally:
        for dht in dhts:
            dht.shutdown()


def test_single_peer_epoch_progress():
    """A LONE peer's own report completes the epoch: readiness must arrive within
    ~a second, not after max_refresh_period (regression: the fetcher slept out its
    adaptive refresh while the local report already crossed the target, and stale
    self-records in the DHT shadowed fresh local progress)."""
    dht = DHT(start=True)
    tracker = None
    try:
        tracker = ProgressTracker(dht, "solo_run", target_batch_size=16,
                                  min_refresh_period=0.2, default_refresh_period=0.3)
        tracker.report_local_progress(0, 16)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not tracker.ready_to_update_epoch:
            time.sleep(0.1)
        assert tracker.ready_to_update_epoch, tracker.global_progress
        assert tracker.global_progress.samples_accumulated >= 16
    finally:
        if tracker is not None:
            tracker.shutdown()
        dht.shutdown()
