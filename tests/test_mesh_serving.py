"""Mesh-sharded block serving (VERDICT r4 next-round #4): a served block whose
params + KV caches are NamedSharding global arrays over a device mesh, behind the
UNCHANGED Server/RemoteSequential path — clients get token-identical generations
whether one device or the whole mesh answers. Re-designed reference role: the
single-CUDA-device executor of hivemind/moe/server/runtime.py:22-199."""

import time

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec

from hivemind_tpu.dht import DHT
from hivemind_tpu.moe.server.llama_loader import (
    LlamaCheckpointConfig,
    decode_cache_bytes,
    load_llama_blocks,
    plan_block_capacity,
    predict_block_param_bytes,
)
from hivemind_tpu.moe.server.mesh_backend import MeshModuleBackend
from hivemind_tpu.moe.server.server import Server

from test_llama_loader import HID, LAYERS, _write_checkpoint


def _tp_mesh() -> Mesh:
    devices = np.array(jax.devices())
    return Mesh(devices.reshape(len(devices)), ("tp",))


def test_mesh_backend_shards_params_and_caches(tmp_path):
    _write_checkpoint(tmp_path)
    mesh = _tp_mesh()
    backends, _config = load_llama_blocks(tmp_path, uid_prefix="mb.", mesh=mesh)
    backend = backends["mb.0"]
    assert isinstance(backend, MeshModuleBackend)

    # the big kernels really live distributed: each device holds 1/8th
    sharded_leaves = [
        leaf
        for leaf in jax.tree_util.tree_leaves(backend.params)
        if backend.leaf_spec(leaf) != PartitionSpec()
    ]
    assert sharded_leaves, "no parameter leaf was sharded"
    for leaf in sharded_leaves:
        shard = leaf.addressable_shards[0]
        assert shard.data.size == leaf.size // len(mesh.devices.flat)
    assert backend.param_bytes_per_device() < backend.param_bytes()

    # KV decode caches shard through the session-manager hook
    cache_k, cache_v = backend.module.init_decode_cache(2, 32)
    sharded_k, sharded_v = backend.shard_decode_cache(cache_k, cache_v)
    assert sharded_k.sharding.spec != PartitionSpec(*([None] * sharded_k.ndim)) or (
        sharded_k.shape[-2] % len(mesh.devices.flat) != 0
    )
    info = backend.get_info()
    assert info["mesh_devices"] == len(mesh.devices.flat)


def test_mesh_sharded_server_is_token_identical_over_rpc(tmp_path):
    """The same checkpoint served twice from one process — once mesh-sharded,
    once single-device — through the same Server/RemoteSequential stack: greedy
    decode produces IDENTICAL tokens (GSPMD may reorder reductions, so hidden
    states match to tolerance and the argmax chain exactly)."""
    from hivemind_tpu.moe import RemoteSequential

    _write_checkpoint(tmp_path)
    mesh = _tp_mesh()
    backends_mesh, _ = load_llama_blocks(tmp_path, uid_prefix="meshed.", mesh=mesh)
    backends_single, _ = load_llama_blocks(tmp_path, uid_prefix="single.")
    dht = DHT(start=True)
    server = Server(dht, {**backends_mesh, **backends_single}, decode_max_len=64)
    client_dht = None
    try:
        server.run_in_background(await_ready=True)
        time.sleep(1.0)
        client_dht = DHT(initial_peers=[str(m) for m in dht.get_visible_maddrs()], start=True)
        rng = np.random.RandomState(5)
        prompt_len, steps = 6, 6
        hidden = rng.randn(1, prompt_len, HID).astype(np.float32)

        outputs = {}
        for prefix in ("meshed.", "single."):
            pipe = RemoteSequential(client_dht, prefix, LAYERS)
            chunks = [np.asarray(pipe.decode_step(hidden, f"tok_{prefix}", reset=True))]
            # greedy-style chain: each step feeds the previous step's output back,
            # so ANY divergence compounds — the strongest identity check the
            # hidden-state interface allows
            for _ in range(steps):
                chunks.append(
                    np.asarray(pipe.decode_step(chunks[-1][:, -1:], f"tok_{prefix}"))
                )
            outputs[prefix] = np.concatenate(chunks, axis=1)

        meshed, single = outputs["meshed."], outputs["single."]
        assert meshed.shape == single.shape
        # blocks COMPUTE in bf16 and GSPMD reorders reductions, and the feedback
        # chain compounds the epsilon across 6 steps — the norm check is loose;
        # the argmax chain below is the exact assertion
        rel_err = np.linalg.norm(meshed - single) / np.linalg.norm(single)
        assert rel_err < 3e-2, rel_err
        # token-identical: a greedy head reading either stream picks the same ids
        proj = rng.randn(HID, 64).astype(np.float32)  # a fixed surrogate LM head
        assert np.array_equal(
            np.argmax(meshed @ proj, axis=-1), np.argmax(single @ proj, axis=-1)
        )
    finally:
        if client_dht is not None:
            client_dht.shutdown()
        server.shutdown()
        dht.shutdown()


def test_hbm_planning_7b_mesh_pooling():
    """The regime the mesh tier exists for, at REAL 7B shapes: with a 600 MB
    per-chip budget one chip cannot hold even one fp32 block, but an 8-device
    mesh pools to several blocks — and the sharded per-device residency math
    confirms each chip holds 1/8th of a block."""
    config = LlamaCheckpointConfig(
        hidden_size=4096, num_attention_heads=32, num_key_value_heads=32,
        intermediate_size=11008, num_hidden_layers=32,
    )
    block = predict_block_param_bytes(config)
    assert block > 700 * 1024**2  # ~810 MB fp32: genuinely 7B-scale

    budget = 600 * 1024**2
    cache = decode_cache_bytes(config, batch=1, max_len=512)
    single = plan_block_capacity(
        block, hbm_bytes=budget, decode_sessions=2, cache_bytes_per_session_block=cache
    )
    pooled = plan_block_capacity(
        block, hbm_bytes=budget, decode_sessions=2, cache_bytes_per_session_block=cache,
        mesh_devices=8,
    )
    assert single == 0, single  # one chip: not even one block
    assert pooled >= 4, pooled  # the slice: several blocks
