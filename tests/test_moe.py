"""MoE end-to-end: server + remote expert numerics vs local module, gradients through
RPC, beam search over a real swarm, mixture forward, checkpoints
(scope: reference tests/test_moe.py + test_expert_backend.py + test_connection_handler.py)."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from hivemind_tpu.dht import DHT
from hivemind_tpu.moe import (
    ExpertInfo,
    ModuleBackend,
    RemoteExpert,
    RemoteMixtureOfExperts,
    RemoteSwitchMixtureOfExperts,
    Server,
    declare_experts,
    get_experts,
    is_valid_uid,
    split_uid,
)
from hivemind_tpu.moe.client.beam_search import MoEBeamSearcher
from hivemind_tpu.moe.server.layers import FeedforwardExpert, name_to_block
from hivemind_tpu.utils.timed_storage import get_dht_time

HID = 32


def test_expert_uid_utils():
    assert is_valid_uid("ffn.0.3") and is_valid_uid("expert.5")
    assert not is_valid_uid("ffn.") and not is_valid_uid("ffn") and not is_valid_uid("ffn.01")
    assert split_uid("ffn.5.12") == ("ffn.5.", 12)


def test_module_backend_numerics():
    module = FeedforwardExpert(HID)
    backend = ModuleBackend(
        "test.0", module, optimizer=optax.sgd(1e-2),
        sample_input=np.zeros((4, HID), np.float32), max_batch_size=64,
    )
    x = np.random.RandomState(0).randn(5, HID).astype(np.float32)
    out = backend.forward(x)[0]
    expected = module.apply({"params": backend.params}, jnp.asarray(x))
    assert np.allclose(out, np.asarray(expected), atol=2e-2)  # bf16 compute tolerance

    # backward returns input grads AND trains the expert
    params_before = [np.asarray(l).copy() for l in jax.tree_util.tree_leaves(backend.params)]
    grad_out = np.ones_like(out)
    grad_in = backend.backward(x, grad_out)[0]
    assert grad_in.shape == x.shape and np.isfinite(grad_in).all()
    params_after = [np.asarray(l) for l in jax.tree_util.tree_leaves(backend.params)]
    assert any(not np.array_equal(a, b) for a, b in zip(params_before, params_after))
    assert backend.update_count == 1

    # state round trip
    blob = backend.state_dict()
    backend.load_state_dict(blob)
    assert backend.update_count == 1


def make_server(dht=None, uids=("ffn_test.0.0", "ffn_test.0.1", "ffn_test.1.0", "ffn_test.1.1")):
    return Server.create(
        expert_uids=list(uids), expert_cls="ffn", hidden_dim=HID,
        dht=dht, start=True, max_batch_size=256,
        optim_factory=lambda: optax.sgd(1e-3),
    )


def test_remote_expert_forward_backward():
    server = make_server()
    try:
        import time
        time.sleep(1.0)  # let experts declare
        infos = get_experts(server.dht, ["ffn_test.0.0"])
        assert infos[0] is not None
        client_dht = DHT(initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True)
        expert = RemoteExpert(infos[0], client_dht.node.p2p)
        # info fetch
        assert expert.info["max_batch_size"] == 256

        x = jnp.asarray(np.random.RandomState(0).randn(3, HID), jnp.float32)
        out = expert(x)
        backend = server.backends["ffn_test.0.0"]
        expected = backend.module.apply({"params": backend.params}, x)
        assert np.allclose(np.asarray(out), np.asarray(expected), atol=2e-2)

        # gradients flow through the RPC (and train the server-side expert)
        def loss_fn(xx):
            return jnp.sum(expert(xx) ** 2)

        grads = jax.grad(loss_fn)(x)
        assert grads.shape == x.shape and bool(jnp.isfinite(grads).all())
        assert backend.update_count >= 1
        client_dht.shutdown()
    finally:
        server.shutdown()
        server.dht.shutdown()


def test_beam_search_finds_best_experts():
    server = make_server()
    try:
        import time
        time.sleep(1.0)
        searcher = MoEBeamSearcher(server.dht, "ffn_test.", grid_size=(2, 2))
        # score dimension 0: prefer row 1; dimension 1: prefer col 0
        grid_scores = [np.array([0.0, 5.0], np.float32), np.array([3.0, 0.0], np.float32)]
        found = searcher.find_best_experts(grid_scores, beam_size=3)
        assert found, "beam search found nothing"
        assert found[0].uid == "ffn_test.1.0"  # argmax of score sums
        uids = [info.uid for info in found]
        assert uids == sorted(uids, key=lambda u: -sum(
            grid_scores[d][int(c)] for d, c in enumerate(u.split(".")[1:])
        ))
    finally:
        server.shutdown()
        server.dht.shutdown()


def test_remote_mixture_of_experts():
    server = make_server()
    try:
        import time
        time.sleep(1.0)
        client_dht = DHT(initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True)
        moe = RemoteMixtureOfExperts(
            dht=client_dht, in_features=HID, grid_size=(2, 2),
            uid_prefix="ffn_test.", k_best=2, k_min=1,
        )
        x = jnp.asarray(np.random.RandomState(1).randn(5, HID), jnp.float32)
        out = moe(x)
        assert out.shape == (5, HID)
        assert bool(jnp.isfinite(out).all())

        switch = RemoteSwitchMixtureOfExperts(
            dht=client_dht, in_features=HID, grid_size=(2, 2), uid_prefix="ffn_test.",
        )
        out_switch = switch(x)
        assert out_switch.shape == (5, HID)
        assert any(u.sum() > 0 for u in switch.grid_utilization)
        client_dht.shutdown()
    finally:
        server.shutdown()
        server.dht.shutdown()


def test_background_server_contextmanager():
    from hivemind_tpu.moe import background_server

    with background_server(
        expert_uids=["bgctx.0"], expert_cls="nop", hidden_dim=8,
        optim_factory=lambda: optax.sgd(1e-3),
    ) as (dht, server):
        assert dht.is_alive and "bgctx.0" in server.backends
        out = server.backends["bgctx.0"].forward(np.ones((2, 8), np.float32))[0]
        assert out.shape == (2, 8)
    assert not dht.is_alive  # context exit shuts everything down


def test_checkpoints_roundtrip(tmp_path):
    from hivemind_tpu.moe.server.checkpoints import load_experts, store_experts

    module = FeedforwardExpert(HID)
    backend = ModuleBackend(
        "ck.0", module, optimizer=optax.sgd(1e-2),
        sample_input=np.zeros((2, HID), np.float32),
    )
    x = np.random.randn(4, HID).astype(np.float32)
    backend.backward(x, np.ones((4, HID), np.float32))  # mutate params
    store_experts({"ck.0": backend}, tmp_path)

    fresh = ModuleBackend(
        "ck.0", FeedforwardExpert(HID), optimizer=optax.sgd(1e-2),
        sample_input=np.zeros((2, HID), np.float32), rng_seed=99,
    )
    assert load_experts({"ck.0": fresh}, tmp_path) == 1
    old_leaf = jax.tree_util.tree_leaves(backend.params)[0]
    new_leaf = jax.tree_util.tree_leaves(fresh.params)[0]
    assert np.allclose(np.asarray(old_leaf), np.asarray(new_leaf))
    assert fresh.update_count == 1


def test_multi_tensor_expert_backend_and_remote():
    """Experts with several inputs AND several outputs work locally and over RPC
    (reference module_backend.py:68-74 nested schemas)."""
    import flax.linen as nn

    class TwoInTwoOut(nn.Module):
        hid: int

        @nn.compact
        def __call__(self, x, y):
            h = nn.Dense(self.hid)(x) + y
            return h, jnp.tanh(h)

    backend = ModuleBackend(
        "multi.0", TwoInTwoOut(HID), optimizer=optax.sgd(1e-3),
        sample_inputs=[np.zeros((2, HID), np.float32), np.zeros((2, HID), np.float32)],
        max_batch_size=64,
    )
    assert backend.num_inputs == 2 and backend.num_outputs == 2
    rng = np.random.RandomState(0)
    x, y = rng.randn(3, HID).astype(np.float32), rng.randn(3, HID).astype(np.float32)
    out1, out2 = backend.forward(x, y)
    ref1, ref2 = backend.module.apply({"params": backend.params}, jnp.asarray(x), jnp.asarray(y))
    assert np.allclose(out1, np.asarray(ref1), atol=1e-4)
    assert np.allclose(out2, np.asarray(ref2), atol=1e-4)
    grads = backend.backward(x, y, np.ones_like(out1), np.ones_like(out2))
    assert len(grads) == 2 and grads[0].shape == x.shape and grads[1].shape == y.shape
    assert backend.update_count == 1

    # over RPC: schemas travel through rpc_info, both passes work, grads flow to
    # EVERY input
    dht = DHT(start=True)
    # exact-numerics fixture: wire precision is covered by the compressed-RPC
    # equivalence suite (test_serving_compression.py)
    server = Server(dht, {"multi.0": backend}, activation_compression="none")
    try:
        server.run_in_background(await_ready=True)
        client_dht = DHT(initial_peers=[str(m) for m in dht.get_visible_maddrs()], start=True)
        expert = RemoteExpert(ExpertInfo("multi.0", dht.peer_id), client_dht.node.p2p)
        r_out1, r_out2 = expert(jnp.asarray(x), jnp.asarray(y))
        # the local backward above trained the expert: compare against CURRENT params
        now1, now2 = backend.forward(x, y)
        assert np.allclose(np.asarray(r_out1), now1, atol=1e-4)
        assert np.allclose(np.asarray(r_out2), now2, atol=1e-4)

        def loss_fn(xx, yy):
            a, b = expert(xx, yy)
            return jnp.sum(a ** 2) + jnp.sum(b ** 2)

        gx, gy = jax.grad(loss_fn, argnums=(0, 1))(jnp.asarray(x), jnp.asarray(y))
        assert gx.shape == x.shape and gy.shape == y.shape
        assert bool(jnp.isfinite(gx).all()) and bool(jnp.isfinite(gy).all())
        assert bool((jnp.abs(gy) > 0).any())
        client_dht.shutdown()
    finally:
        server.shutdown()
        dht.shutdown()


def test_call_many_masks_dead_experts():
    """RemoteCallMany: a dead expert is masked out (k_min still satisfied), gradients
    flow through the survivors, and k_min violations raise."""
    from hivemind_tpu.moe.client.call_many import RemoteCallMany

    server = make_server()
    try:
        import time
        time.sleep(1.0)
        client_dht = DHT(initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True)
        infos = get_experts(server.dht, ["ffn_test.0.0", "ffn_test.0.1"])
        good = [RemoteExpert(info, client_dht.node.p2p) for info in infos]
        dead = RemoteExpert(ExpertInfo("ffn_test.9.9", server.dht.peer_id), client_dht.node.p2p)

        x = jnp.asarray(np.random.RandomState(2).randn(4, HID), jnp.float32)
        rows = [[good[0], dead], [good[1], dead], [good[0], good[1]], [good[1], dead]]
        rcm = RemoteCallMany(rows, k_min=1, backward_k_min=1, forward_timeout=20)
        outputs, alive = rcm(x)
        alive = np.asarray(alive)
        assert outputs.shape == (4, 2, HID)
        assert alive[:, 0].all() and alive[2, 1] and not alive[0, 1] and not alive[3, 1]

        def loss_fn(xx):
            out, live = RemoteCallMany(rows, k_min=1, forward_timeout=20)(xx)
            return jnp.sum(out ** 2)

        grads = jax.grad(loss_fn)(x)
        assert grads.shape == x.shape and bool(jnp.isfinite(grads).all())

        # k_min=2 with only one live expert on a row must raise
        rcm_strict = RemoteCallMany([[good[0], dead]], k_min=2, forward_timeout=10)
        with pytest.raises(Exception):
            jax.block_until_ready(rcm_strict(x[:1])[0])
        client_dht.shutdown()
    finally:
        server.shutdown()
        server.dht.shutdown()


def test_deterministic_dropout_expert():
    """det_dropout: the mask is a second input; forward/backward see the same mask
    over RPC and the mask gates the gradient (reference layers/dropout.py)."""
    server = Server.create(
        expert_uids=["drop.0"], expert_cls="det_dropout", hidden_dim=16,
        start=True, optim_factory=lambda: optax.sgd(1e-3),
    )
    try:
        import time
        time.sleep(0.5)
        client_dht = DHT(initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True)
        expert = RemoteExpert(ExpertInfo("drop.0", server.dht.peer_id), client_dht.node.p2p)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(3, 16), jnp.float32)
        mask = jnp.asarray((rng.rand(3, 16) > 0.2), jnp.float32)
        out = expert(x, mask)
        backend = server.backends["drop.0"]
        expected = backend.module.apply({"params": backend.params}, x, mask)
        assert np.allclose(np.asarray(out), np.asarray(expected), atol=2e-2)

        # gradient wrt x must be zero exactly where the mask dropped the input
        grads = jax.grad(lambda xx: jnp.sum(expert(xx, mask) ** 2))(x)
        dropped = np.asarray(mask) == 0
        assert np.allclose(np.asarray(grads)[dropped], 0.0, atol=1e-6)
        assert np.abs(np.asarray(grads)[~dropped]).max() > 0
        client_dht.shutdown()
    finally:
        server.shutdown()
        server.dht.shutdown()


def test_remote_sequential_pipeline():
    """Petals-style pipelining: a 3-block model served across TWO servers runs and
    backpropagates end-to-end through chained remote calls; killing the server of a
    block and re-declaring it elsewhere fails over transparently."""
    from hivemind_tpu.moe import RemoteSequential

    # server A hosts blocks 0 and 2, server B hosts block 1 (split pipeline)
    server_a = Server.create(
        expert_uids=["blk.0", "blk.2"], expert_cls="transformer", hidden_dim=16,
        start=True, optim_factory=lambda: optax.sgd(1e-3),
    )
    dht_b = DHT(initial_peers=[str(m) for m in server_a.dht.get_visible_maddrs()], start=True)
    server_b = Server.create(
        expert_uids=["blk.1"], expert_cls="transformer", hidden_dim=16,
        dht=dht_b, start=True, optim_factory=lambda: optax.sgd(1e-3),
    )
    client_dht = None
    try:
        import time
        time.sleep(1.0)
        client_dht = DHT(initial_peers=[str(m) for m in server_a.dht.get_visible_maddrs()], start=True)
        pipe = RemoteSequential(client_dht, "blk.", 3, update_period=2.0)
        x = jnp.asarray(np.random.RandomState(0).randn(2, 64, 16), jnp.float32)

        out = pipe(x)
        assert out.shape == x.shape
        # matches running the three backends locally in order
        expected = x
        for uid, backend_server in (("blk.0", server_a), ("blk.1", server_b), ("blk.2", server_a)):
            backend = backend_server.backends[uid]
            expected = backend.module.apply({"params": backend.params}, expected)
        assert np.allclose(np.asarray(out), np.asarray(expected), atol=5e-2)

        # gradients flow through the WHOLE pipeline (and train every block server)
        grads = jax.grad(lambda xx: jnp.sum(pipe(xx) ** 2))(x)
        assert grads.shape == x.shape and bool(jnp.isfinite(grads).all())
        assert server_b.backends["blk.1"].update_count >= 1

        # failover: block 1 moves to a new server; the stale cached route must heal
        server_b.shutdown()
        dht_b.shutdown()
        replacement = Server.create(
            expert_uids=["blk.1"], expert_cls="transformer", hidden_dim=16,
            dht=DHT(initial_peers=[str(m) for m in server_a.dht.get_visible_maddrs()], start=True),
            start=True, optim_factory=lambda: optax.sgd(1e-3),
        )
        try:
            time.sleep(2.5)  # cached resolution expires (update_period) + declare
            out2 = pipe(x)
            assert out2.shape == x.shape and bool(jnp.isfinite(out2).all())
        finally:
            replacement.shutdown()
            replacement.dht.shutdown()
    finally:
        if client_dht is not None:
            client_dht.shutdown()
        server_a.shutdown()
        server_a.dht.shutdown()


def test_switch_grid_dropout():
    """grid_dropout masks grid coordinates with -inf gating scores: routing avoids
    dropped coordinates; dropout 1.0 is a no-op (reference switch_moe.py:84-98)."""
    server = make_server()
    try:
        import time
        time.sleep(1.0)
        client_dht = DHT(initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True)
        switch = RemoteSwitchMixtureOfExperts(
            dht=client_dht, in_features=HID, grid_size=(2, 2), uid_prefix="ffn_test.",
            grid_dropout=0.75,
        )
        # force a deterministic mask: keep only row 0 (dim 0) and column 1 (dim 1)
        class _FixedRng:
            def __init__(self):
                self.masks = [np.array([0.0, 1.0]), np.array([1.0, 0.0])]  # < 0.75 keeps

            def uniform(self, low, high, size):
                return np.full(size, 1.0, np.float32)  # no jitter

            def rand(self, size):
                return self.masks.pop(0)

        switch._jitter_rng = _FixedRng()
        x = jnp.asarray(np.random.RandomState(3).randn(4, HID), jnp.float32)
        out = switch(x)
        assert out.shape == (4, HID) and bool(jnp.isfinite(out).all())
        # with rows {0} and cols {1} kept, the only routable expert is 0.1
        utilization_rows, utilization_cols = switch.grid_utilization
        assert utilization_rows[0] > utilization_rows[1]
        assert utilization_cols[1] > utilization_cols[0]
        client_dht.shutdown()
    finally:
        server.shutdown()
        server.dht.shutdown()


def test_causal_block_pipeline_decode():
    """Causal decoder blocks over RemoteSequential: positions only depend on their
    prefix (changing the suffix leaves earlier outputs bit-identical THROUGH the
    RPC), which makes fixed-schema right-padded autoregressive decoding exact."""
    from hivemind_tpu.moe import RemoteSequential

    server = Server.create(
        expert_uids=["cblk.0", "cblk.1"], expert_cls="causal_transformer", hidden_dim=16,
        start=True, optim_factory=lambda: optax.sgd(1e-4),
    )
    client_dht = None
    try:
        import time
        time.sleep(1.0)
        client_dht = DHT(initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True)
        pipe = RemoteSequential(client_dht, "cblk.", 2)

        rng = np.random.RandomState(0)
        prefix = rng.randn(1, 64, 16).astype(np.float32)
        variant = prefix.copy()
        variant[:, 10:] = rng.randn(1, 54, 16)  # different suffix from position 10

        out_a = np.asarray(pipe(jnp.asarray(prefix)))
        out_b = np.asarray(pipe(jnp.asarray(variant)))
        # causality through two remote blocks: positions < 10 are identical
        np.testing.assert_array_equal(out_a[:, :10], out_b[:, :10])
        assert np.abs(out_a[:, 10:] - out_b[:, 10:]).max() > 0  # suffix does differ
    finally:
        if client_dht is not None:
            client_dht.shutdown()
        server.shutdown()
        server.dht.shutdown()


def test_llama_block_gqa_causality_and_rope():
    """LlamaBlockExpert (RMSNorm + RoPE + GQA + SwiGLU): causal, GQA head wiring
    sound, and RoPE gives relative-position-consistent attention (a pure shift of
    content into later positions preserves causality of the earlier ones)."""
    from hivemind_tpu.moe.server.layers.common import LlamaBlockExpert

    block = LlamaBlockExpert(hidden_dim=16, num_heads=4, num_kv_heads=2)
    rng = np.random.RandomState(0)
    x = rng.randn(2, 32, 16).astype(np.float32)
    params = block.init(jax.random.PRNGKey(0), jnp.asarray(x))
    out = np.asarray(block.apply(params, jnp.asarray(x)))
    assert out.shape == x.shape and np.isfinite(out).all()

    # GQA params: key/value project to kv_heads*head_dim = 8, query to 16
    kernels = jax.tree_util.tree_map(lambda a: a.shape, params)["params"]
    assert kernels["key"]["kernel"] == (16, 8)
    assert kernels["query"]["kernel"] == (16, 16)

    # causality: perturbing the suffix leaves the prefix outputs bit-identical
    y = x.copy()
    y[:, 20:] = rng.randn(2, 12, 16)
    out_y = np.asarray(block.apply(params, jnp.asarray(y)))
    np.testing.assert_array_equal(out[:, :20], out_y[:, :20])
    assert np.abs(out[:, 20:] - out_y[:, 20:]).max() > 0

    # RoPE pin: q·k after rotation depends only on the RELATIVE position, and the
    # rotation is not the identity. Broadcasting one content vector to every
    # position makes apply_rope(x)[0, p, 0] the rotation of that vector at p.
    from hivemind_tpu.moe.server.layers.common import apply_rope

    cq, ck = rng.randn(8).astype(np.float32), rng.randn(8).astype(np.float32)
    rq = np.asarray(apply_rope(jnp.broadcast_to(jnp.asarray(cq), (1, 16, 1, 8))))[0, :, 0]
    rk = np.asarray(apply_rope(jnp.broadcast_to(jnp.asarray(ck), (1, 16, 1, 8))))[0, :, 0]
    scores = rq @ rk.T  # [i, j] = rot(cq, i) . rot(ck, j)
    for shift in (1, 5):
        np.testing.assert_allclose(
            scores[:-shift, :-shift], scores[shift:, shift:], rtol=1e-4, atol=1e-4
        )
    assert np.abs(scores - float(cq @ ck)).max() > 0.1  # identity rope would be flat


def test_llama_block_pipeline_decode():
    """Llama-family blocks served over RemoteSequential (the BASELINE 'Petals-style
    Llama block server' config): prefix outputs are exact through the RPC, so
    right-padded fixed-schema autoregressive decoding works unchanged."""
    from hivemind_tpu.moe import RemoteSequential

    server = Server.create(
        expert_uids=["lblk.0", "lblk.1"], expert_cls="llama_block", hidden_dim=16,
        expert_kwargs={"num_heads": 4, "num_kv_heads": 2},  # GQA through the serving path
        start=True, optim_factory=lambda: optax.sgd(1e-4),
    )
    client_dht = None
    try:
        import time
        time.sleep(1.0)
        client_dht = DHT(initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True)
        pipe = RemoteSequential(client_dht, "lblk.", 2)

        rng = np.random.RandomState(1)
        prefix = rng.randn(1, 64, 16).astype(np.float32)
        variant = prefix.copy()
        variant[:, 7:] = rng.randn(1, 57, 16)

        out_a = np.asarray(pipe(jnp.asarray(prefix)))
        out_b = np.asarray(pipe(jnp.asarray(variant)))
        np.testing.assert_array_equal(out_a[:, :7], out_b[:, :7])
        assert np.abs(out_a[:, 7:] - out_b[:, 7:]).max() > 0
    finally:
        if client_dht is not None:
            client_dht.shutdown()
        server.shutdown()
        server.dht.shutdown()


def test_beam_search_negative_caching():
    """Dead prefixes (grid cells with no declared experts) land in the negative
    cache after one search (reference beam_search.py:60-74,152-160), and cached
    searches still rank live experts correctly."""
    server = make_server()  # declares ffn_test.{0,1}.{0,1}
    try:
        import time
        time.sleep(1.0)
        searcher = MoEBeamSearcher(server.dht, "ffn_test.", grid_size=(4, 2))
        grid_scores = [
            np.array([0.0, 1.0, 10.0, 10.0], np.float32),  # rows 2..3 score best but are dead
            np.array([3.0, 0.0], np.float32),
        ]
        found = searcher.find_best_experts(grid_scores, beam_size=4)
        # rows 2..3 score best but are dead: the beam never proposes them because
        # the DHT prefix dictionary only lists coordinates that were declared
        assert found and found[0].uid == "ffn_test.1.0"
        assert all(info.uid.split(".")[1] in ("0", "1") for info in found)

        # a prefix tree with NO experts at all gets negative-cached after one miss
        ghost = MoEBeamSearcher(server.dht, "ghost.", grid_size=(2, 2))
        assert ghost.find_best_experts([np.ones(2, np.float32)] * 2, beam_size=2) == []
        assert len(ghost._negative_cache) > 0, "dead prefix was not negative-cached"
        assert ghost.find_best_experts([np.ones(2, np.float32)] * 2, beam_size=2) == []

        # the live searcher's second query (now possibly cache-assisted) must
        # still rank live experts identically
        again = searcher.find_best_experts(grid_scores, beam_size=4)
        assert [i.uid for i in again] == [i.uid for i in found]
    finally:
        server.shutdown()
        server.dht.shutdown()


def test_decode_cache_matches_full_forward():
    """KV-cache decode (prefill + per-token steps) is bit-identical to the full
    causal forward for both decoder block families (GQA caches stay compact)."""
    from hivemind_tpu.moe.server.layers.common import CausalTransformerExpert, LlamaBlockExpert

    rng = np.random.RandomState(0)
    for cls, kwargs in (
        (CausalTransformerExpert, dict(num_heads=4)),
        (LlamaBlockExpert, dict(num_heads=4, num_kv_heads=2)),
    ):
        block = cls(hidden_dim=16, **kwargs)
        x = jnp.asarray(rng.randn(2, 12, 16).astype(np.float32))
        params = block.init(jax.random.PRNGKey(0), x)
        full = np.asarray(block.apply(params, x))

        cache_k, cache_v = block.init_decode_cache(batch=2, max_len=32)
        y, cache_k, cache_v = block.apply(params, x[:, :5], cache_k, cache_v, 0)
        outs = [np.asarray(y)]
        for t in range(5, 12):
            y, cache_k, cache_v = block.apply(params, x[:, t:t + 1], cache_k, cache_v, t)
            outs.append(np.asarray(y))
        np.testing.assert_array_equal(np.concatenate(outs, axis=1), full)


def test_decode_sessions_over_rpc():
    """Petals-style incremental decoding through the swarm: per-session KV caches
    on the serving peer, driven by RemoteSequential.decode_step — outputs match
    the right-padded full-recompute pipeline exactly, per generated position."""
    import uuid
    from hivemind_tpu.moe import RemoteSequential

    server = Server.create(
        expert_uids=["dblk.0", "dblk.1"], expert_cls="llama_block", hidden_dim=16,
        start=True, optim_factory=lambda: optax.sgd(1e-4),
        # exact decode-vs-recompute math is the subject: bit-exact wire (fp16
        # wire tolerance is covered by test_serving_compression.py)
        activation_compression="none",
    )
    client_dht = None
    try:
        import time
        time.sleep(1.0)
        client_dht = DHT(initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True)
        pipe = RemoteSequential(client_dht, "dblk.", 2)

        rng = np.random.RandomState(3)
        hidden = rng.randn(1, 9, 16).astype(np.float32)  # prompt 6 + 3 decode steps
        session = uuid.uuid4().hex

        # session path: prefill the 6-token prompt, then three 1-token steps
        out_prefill = pipe.decode_step(hidden[:, :6], session, reset=True)
        step_outs = [pipe.decode_step(hidden[:, t:t + 1], session) for t in range(6, 9)]

        # reference path: right-padded full recompute at schema length 64
        padded = np.zeros((1, 64, 16), np.float32)
        padded[:, :9] = hidden
        full = np.asarray(pipe(jnp.asarray(padded)))

        np.testing.assert_allclose(out_prefill, full[:, :6], rtol=1e-5, atol=1e-5)
        for offset, out in enumerate(step_outs):
            np.testing.assert_allclose(out, full[:, 6 + offset:7 + offset], rtol=1e-5, atol=1e-5)

        # a fresh session with the same id on ANOTHER input must reset cleanly
        out_reset = pipe.decode_step(hidden[:, :6], session, reset=True)
        np.testing.assert_allclose(out_reset, out_prefill, rtol=1e-6, atol=1e-6)

        # a continuation on an UNKNOWN session must raise, never silently prefill
        with pytest.raises(RuntimeError, match="no pinned route"):
            pipe.decode_step(hidden[:, :1], "never-prefilled")
        from hivemind_tpu.p2p.p2p import P2PHandlerError

        block0 = pipe._block(0)
        with pytest.raises(P2PHandlerError, match="unknown or expired"):
            block0.decode_np(hidden[:, :1], "server-side-unknown", reset=False)
    finally:
        if client_dht is not None:
            client_dht.shutdown()
        server.shutdown()
        server.dht.shutdown()


def test_decode_span_execution_across_two_servers():
    """A 4-block pipeline split over TWO servers pins two 2-block spans: each
    per-token RPC chains the co-located blocks server-side, and the decoded
    positions still match the right-padded full recompute exactly."""
    import uuid
    from hivemind_tpu.moe import RemoteSequential

    server_a = Server.create(
        expert_uids=["span.0", "span.1"], expert_cls="causal_transformer", hidden_dim=16,
        start=True, optim_factory=lambda: optax.sgd(1e-4),
        activation_compression="none",  # exact span-vs-recompute math is the subject
    )
    server_b = Server.create(
        expert_uids=["span.2", "span.3"], expert_cls="causal_transformer", hidden_dim=16,
        dht=None, start=True, optim_factory=lambda: optax.sgd(1e-4),
        initial_peers=[str(m) for m in server_a.dht.get_visible_maddrs()],
        activation_compression="none",
    )
    client_dht = None
    try:
        import time
        time.sleep(1.5)
        client_dht = DHT(initial_peers=[str(m) for m in server_a.dht.get_visible_maddrs()], start=True)
        pipe = RemoteSequential(client_dht, "span.", 4)

        rng = np.random.RandomState(11)
        hidden = rng.randn(1, 7, 16).astype(np.float32)
        session = uuid.uuid4().hex
        out_prefill = pipe.decode_step(hidden[:, :5], session, reset=True)
        route = pipe._decode_routes[session]["route"]
        assert [len(span) for _block, span in route] == [2, 2], route  # two 2-block spans
        step_outs = [pipe.decode_step(hidden[:, t:t + 1], session) for t in (5, 6)]

        padded = np.zeros((1, 64, 16), np.float32)
        padded[:, :7] = hidden
        full = np.asarray(pipe(jnp.asarray(padded)))
        np.testing.assert_allclose(out_prefill, full[:, :5], rtol=1e-5, atol=1e-5)
        for offset, out in enumerate(step_outs):
            np.testing.assert_allclose(out, full[:, 5 + offset:6 + offset], rtol=1e-5, atol=1e-5)

        # training across the span boundary: gradients flow through both servers'
        # spans (client recovers the boundary activation with one forward sweep)
        # and every block's server-side optimizer steps
        counts_before = [
            server_a.backends["span.0"].update_count, server_a.backends["span.1"].update_count,
            server_b.backends["span.2"].update_count, server_b.backends["span.3"].update_count,
        ]
        grads = jax.grad(lambda xx: jnp.sum(pipe(xx) ** 2))(jnp.asarray(padded))
        assert grads.shape == padded.shape and bool(jnp.isfinite(grads).all())
        counts_after = [
            server_a.backends["span.0"].update_count, server_a.backends["span.1"].update_count,
            server_b.backends["span.2"].update_count, server_b.backends["span.3"].update_count,
        ]
        assert all(after == before + 1 for before, after in zip(counts_before, counts_after)), (
            counts_before, counts_after,
        )
    finally:
        if client_dht is not None:
            client_dht.shutdown()
        for server in (server_b, server_a):
            server.shutdown()
            server.dht.shutdown()


def test_decode_failover_mid_generation_matches_uninterrupted_run():
    """Transparent decode-session failover (VERDICT r3 #3, Petals-class): one of two
    block servers dies MID-GENERATION and a replacement (same uid, same seed-0
    weights) takes over; the client re-prefills it from the retained input history
    and the emitted positions are IDENTICAL to an uninterrupted run — the caller
    never passes reset=True."""
    import time
    import uuid
    from hivemind_tpu.moe import RemoteSequential

    server_a = Server.create(
        expert_uids=["fo.0"], expert_cls="causal_transformer", hidden_dim=16,
        start=True, optim_factory=lambda: optax.sgd(1e-4),
    )
    maddrs = [str(m) for m in server_a.dht.get_visible_maddrs()]
    server_b = Server.create(
        expert_uids=["fo.1"], expert_cls="causal_transformer", hidden_dim=16,
        dht=None, start=True, optim_factory=lambda: optax.sgd(1e-4), initial_peers=maddrs,
    )
    client_dht = server_b2 = None
    try:
        time.sleep(1.5)
        client_dht = DHT(initial_peers=maddrs, start=True)
        pipe = RemoteSequential(client_dht, "fo.", 2, max_retries=4)

        rng = np.random.RandomState(5)
        hidden = rng.randn(1, 8, 16).astype(np.float32)
        prompt, steps = 4, 4

        # reference: uninterrupted generation
        ref_session = uuid.uuid4().hex
        ref = [pipe.decode_step(hidden[:, :prompt], ref_session, reset=True)]
        ref += [pipe.decode_step(hidden[:, t:t + 1], ref_session) for t in range(prompt, prompt + steps)]

        # failover run: same inputs; kill server_b after two generated positions
        session = uuid.uuid4().hex
        outs = [pipe.decode_step(hidden[:, :prompt], session, reset=True)]
        outs += [pipe.decode_step(hidden[:, t:t + 1], session) for t in (prompt, prompt + 1)]

        server_b.shutdown()
        server_b.dht.shutdown()
        server_b2 = Server.create(  # same uid + default rng_seed=0 => same weights
            expert_uids=["fo.1"], expert_cls="causal_transformer", hidden_dim=16,
            dht=None, start=True, optim_factory=lambda: optax.sgd(1e-4), initial_peers=maddrs,
        )
        time.sleep(1.5)  # let the replacement declare fo.1

        outs += [pipe.decode_step(hidden[:, t:t + 1], session) for t in (prompt + 2, prompt + 3)]

        for i, (expected, got) in enumerate(zip(ref, outs)):
            np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5,
                                       err_msg=f"position group {i} diverged after failover")
        # the route really did move to the replacement peer
        new_route = pipe._decode_routes[session]["route"]
        assert any(
            block.peer_id == server_b2.dht.peer_id for block, _span in new_route
        ), "failover did not re-pin onto the replacement server"
    finally:
        if client_dht is not None:
            client_dht.shutdown()
        for server in (server_b2, server_a):
            if server is not None:
                server.shutdown()
                server.dht.shutdown()


def test_decode_failover_with_span_groups():
    """Failover across SPAN-grouped routes: two servers each hosting a 2-block
    span; the second dies mid-generation and the replacement (same uids, seed-0
    weights) is re-prefilled THROUGH the span RPC — emitted positions identical
    to the uninterrupted run, and the recovered route still groups 2+2."""
    import time
    import uuid
    from hivemind_tpu.moe import RemoteSequential

    server_a = Server.create(
        expert_uids=["fs.0", "fs.1"], expert_cls="causal_transformer", hidden_dim=16,
        start=True, optim_factory=lambda: optax.sgd(1e-4),
    )
    maddrs = [str(m) for m in server_a.dht.get_visible_maddrs()]
    server_b = Server.create(
        expert_uids=["fs.2", "fs.3"], expert_cls="causal_transformer", hidden_dim=16,
        dht=None, start=True, optim_factory=lambda: optax.sgd(1e-4), initial_peers=maddrs,
    )
    client_dht = server_b2 = None
    try:
        time.sleep(1.5)
        client_dht = DHT(initial_peers=maddrs, start=True)
        pipe = RemoteSequential(client_dht, "fs.", 4, max_retries=4)

        rng = np.random.RandomState(9)
        hidden = rng.randn(1, 7, 16).astype(np.float32)
        prompt = 4

        ref_session = uuid.uuid4().hex
        ref = [pipe.decode_step(hidden[:, :prompt], ref_session, reset=True)]
        ref += [pipe.decode_step(hidden[:, t:t + 1], ref_session) for t in range(prompt, 7)]

        session = uuid.uuid4().hex
        outs = [pipe.decode_step(hidden[:, :prompt], session, reset=True)]
        outs.append(pipe.decode_step(hidden[:, prompt:prompt + 1], session))
        assert [len(span) for _b, span in pipe._decode_routes[session]["route"]] == [2, 2]

        server_b.shutdown()
        server_b.dht.shutdown()
        server_b = None  # intentionally dead: keep it out of the finally sweep
        server_b2 = Server.create(
            expert_uids=["fs.2", "fs.3"], expert_cls="causal_transformer", hidden_dim=16,
            dht=None, start=True, optim_factory=lambda: optax.sgd(1e-4), initial_peers=maddrs,
        )
        time.sleep(1.5)
        outs += [pipe.decode_step(hidden[:, t:t + 1], session) for t in (prompt + 1, prompt + 2)]

        for i, (expected, got) in enumerate(zip(ref, outs)):
            np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5,
                                       err_msg=f"position group {i} diverged after span failover")
        assert [len(span) for _b, span in pipe._decode_routes[session]["route"]] == [2, 2]
    finally:
        if client_dht is not None:
            client_dht.shutdown()
        for server in (server_b2, server_b, server_a):
            if server is not None:
                server.shutdown()
                server.dht.shutdown()


def test_span_fallback_for_span_unaware_server():
    """Mixed-swarm capability negotiation: when a server does not advertise
    span_support (an older build would run only the head block and silently
    return a wrong result), the client must fall back to per-block calls."""
    from hivemind_tpu.moe import RemoteSequential

    server = Server.create(
        expert_uids=["nospan.0", "nospan.1"], expert_cls="causal_transformer", hidden_dim=16,
        start=True, optim_factory=lambda: optax.sgd(1e-4),
    )
    client_dht = None
    try:
        import time
        time.sleep(1.0)
        client_dht = DHT(initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True)
        pipe = RemoteSequential(client_dht, "nospan.", 2)

        # this server DOES advertise span support: grouping forms one 2-block span
        groups = pipe._grouped_range(0, 2)
        assert [len(uids) for _head, uids in groups] == [2], groups
        peer_id = groups[0][0].peer_id

        # a span-unaware peer (negative capability cache, as _peer_supports_spans
        # records after probing an older server's rpc_info) falls back to
        # per-block grouping — and the pipeline still computes correctly
        pipe._span_support[peer_id] = False
        groups = pipe._grouped_range(0, 2)
        assert [len(uids) for _head, uids in groups] == [1, 1], groups
        assert all(head.span is None for head, _uids in groups)
        x = jnp.asarray(np.random.RandomState(5).randn(1, 64, 16), jnp.float32)
        out = pipe(x)
        assert out.shape == x.shape and bool(jnp.isfinite(out).all())
    finally:
        if client_dht is not None:
            client_dht.shutdown()
        server.shutdown()
        server.dht.shutdown()


def test_span_forward_retry_restarts_from_original_input():
    """Regression: a mid-chain failure must retry from the ORIGINAL input, not the
    partially-advanced activation — otherwise the blocks that already ran are
    silently applied twice and the custom_vjp primal is corrupted on exactly the
    failover path the retry exists for."""
    from hivemind_tpu.moe import RemoteSequential

    pipe = RemoteSequential.__new__(RemoteSequential)
    pipe.max_retries = 2
    calls = {"attempt": 0}

    class FakeHead:
        def __init__(self, add, fail_once):
            self.add, self.fail_once = add, fail_once

        def forward_np(self, x):
            if self.fail_once and calls["attempt"] == 0:
                calls["attempt"] += 1
                raise ConnectionError("peer died mid-chain")
            return (x + self.add,)

    def grouped_range(start, stop, force=False):
        return [(FakeHead(1.0, fail_once=False), ["b.0"]),
                (FakeHead(10.0, fail_once=True), ["b.1"])]

    pipe._grouped_range = grouped_range
    out = pipe._span_forward(0, 2, np.zeros((1,), np.float32))
    # first attempt applied +1 then died; a buggy retry would re-apply +1 (out=12)
    assert float(out[0]) == 11.0, out


def test_drain_cancellation_releases_pins_and_unblocks_callers():
    """Killing the drainer mid-batch (server shutdown, loop teardown) must drop the
    eviction pins, cancel stranded caller futures, and leave sessions evictable —
    a leaked pin makes a session permanently un-evictable (round-3 advisor,
    decode_session.py:252)."""
    import asyncio
    import threading
    import uuid

    from hivemind_tpu.moe.server.decode_session import DecodeSessionManager
    from hivemind_tpu.moe.server.layers.common import CausalTransformerExpert

    module = CausalTransformerExpert(hidden_dim=16, num_heads=4)
    backend = ModuleBackend(
        "pin.0", module, optimizer=optax.sgd(1e-3),
        sample_input=np.zeros((1, 4, 16), np.float32), max_batch_size=8,
    )
    manager = DecodeSessionManager({"pin.0": backend}, max_len=32)
    assert manager.batching_enabled
    rng = np.random.RandomState(0)
    sid = uuid.uuid4().hex
    manager.decode("pin.0", sid, rng.randn(1, 4, 16).astype(np.float32), reset=True)
    # a second recently-active session keeps the continuous-batching (drainer)
    # path engaged — a lone stream routes onto the direct path since ISSUE 10
    manager.decode("pin.0", uuid.uuid4().hex, rng.randn(1, 4, 16).astype(np.float32), reset=True)

    release, entered = threading.Event(), threading.Event()

    def stuck_batch(uid, entries):
        entered.set()
        release.wait(10)
        raise RuntimeError("batch aborted")

    manager._decode_batch = stuck_batch

    async def scenario():
        step = asyncio.create_task(
            manager.decode_async("pin.0", sid, rng.randn(1, 1, 16).astype(np.float32), False)
        )
        await asyncio.get_running_loop().run_in_executor(None, entered.wait, 10)
        drainer = manager._drainers["pin.0"]
        drainer.cancel()
        with pytest.raises(asyncio.CancelledError):
            await drainer
        release.set()
        with pytest.raises(asyncio.CancelledError):
            await step
        assert manager._in_flight == {}, "eviction pins leaked after drain cancellation"

    asyncio.run(scenario())


def test_decode_continuous_batching_many_clients():
    """Concurrent single-token steps from MANY client sessions are merged into one
    vmapped device call (continuous batching) — every client's tokens must match
    the sequential unbatched path bit-for-bit in fp32 tolerance."""
    import uuid
    from concurrent.futures import ThreadPoolExecutor

    from hivemind_tpu.moe import RemoteSequential

    server = Server.create(
        expert_uids=["cbat.0"], expert_cls="causal_transformer", hidden_dim=16,
        start=True, optim_factory=lambda: optax.sgd(1e-4),
        # batched-vs-direct device math is the subject: bit-exact wire (fp16
        # wire tolerance is covered by test_serving_compression.py)
        activation_compression="none",
    )
    client_dht = None
    try:
        import time
        time.sleep(1.0)
        client_dht = DHT(initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True)
        pipe = RemoteSequential(client_dht, "cbat.", 1)

        num_clients, prompt, steps = 5, 4, 3
        rng = np.random.RandomState(7)
        inputs = [rng.randn(1, prompt + steps, 16).astype(np.float32) for _ in range(num_clients)]

        # reference: each client decoded alone, sequentially (exercises the direct path
        # via fresh sessions; single calls still batch trivially with themselves)
        expected = []
        for hidden in inputs:
            session = uuid.uuid4().hex
            pipe.decode_step(hidden[:, :prompt], session, reset=True)
            expected.append([
                pipe.decode_step(hidden[:, t:t + 1], session)
                for t in range(prompt, prompt + steps)
            ])

        # concurrent: all clients step in lockstep from threads, so their 1-token
        # requests pile into the same flush windows server-side
        sessions = [uuid.uuid4().hex for _ in range(num_clients)]
        for hidden, session in zip(inputs, sessions):
            pipe.decode_step(hidden[:, :prompt], session, reset=True)
        manager = server.handler.decode_sessions
        assert manager.batching_enabled
        fns_before = len(manager._batched_fns)

        def one_step(args):
            client, t = args
            return client, pipe.decode_step(inputs[client][:, t:t + 1], sessions[client])

        with ThreadPoolExecutor(num_clients) as pool:
            for t in range(prompt, prompt + steps):
                outs = dict(pool.map(one_step, [(c, t) for c in range(num_clients)]))
                for client in range(num_clients):
                    np.testing.assert_allclose(
                        outs[client], expected[client][t - prompt], rtol=1e-5, atol=1e-5,
                    )
        assert len(manager._batched_fns) > fns_before, "no batched step was ever compiled"
    finally:
        if client_dht is not None:
            client_dht.shutdown()
        server.shutdown()
        server.dht.shutdown()


def test_decode_prefill_streams_over_unary_cap():
    """A prefill chunk above the 2 MiB unary split streams through
    rpc_decode_stream and still matches the session's incremental math."""
    import uuid
    from hivemind_tpu.moe import RemoteSequential

    server = Server.create(
        expert_uids=["big.0"], expert_cls="causal_transformer", hidden_dim=512,
        decode_max_len=1200, start=True, optim_factory=lambda: optax.sgd(1e-4),
    )
    client_dht = None
    try:
        import time
        time.sleep(1.0)
        client_dht = DHT(initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True)
        pipe = RemoteSequential(client_dht, "big.", 1)
        rng = np.random.RandomState(0)
        prompt = rng.randn(1, 1100, 512).astype(np.float32)  # 2.25 MB > unary cap
        session = uuid.uuid4().hex
        out = pipe.decode_step(prompt, session, reset=True)
        assert out.shape == (1, 1100, 512) and np.isfinite(out).all()
        # one incremental token afterwards proves the streamed prefill seeded the cache
        nxt = pipe.decode_step(prompt[:, :1], session)
        assert nxt.shape == (1, 1, 512) and np.isfinite(nxt).all()
    finally:
        if client_dht is not None:
            client_dht.shutdown()
        server.shutdown()
        server.dht.shutdown()
