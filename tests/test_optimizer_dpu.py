"""Round-2 optimizer features: Delayed Parameter Updates (background epoch
transitions), delta-rule state averaging, aux-peer schema bootstrap, user-level
checkpointing with schedule replay, and the one-epoch-grace reload rule
(VERDICT r1 items 4, 5, 7, 8)."""

import threading
import time
from types import SimpleNamespace

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from hivemind_tpu.dht import DHT
from hivemind_tpu.optim import GradientAverager, Optimizer, TrainingStateAverager

from swarm_utils import launch_dht_swarm


def _toy_problem(seed=0):
    rng = np.random.RandomState(seed)
    true_w = rng.randn(8).astype(np.float32)
    features = rng.randn(256, 8).astype(np.float32)
    targets = features @ true_w

    @jax.jit
    def loss_and_grad(params, x, y):
        def loss_fn(p):
            return jnp.mean((x @ p["w"] - y) ** 2)

        return jax.value_and_grad(loss_fn)(params)

    return features, targets, loss_and_grad


def test_dpu_overlapped_convergence():
    """delay_optimizer_step=True: step() must return while an epoch transition is
    still in flight at least once, training must keep going, and the loss must drop."""
    features, targets, loss_and_grad = _toy_problem()
    dhts = launch_dht_swarm(2)
    results, errors = {}, []
    overlap_observed = threading.Event()

    def run_peer(index: int, dht: DHT):
        try:
            params = {"w": jnp.zeros(8, jnp.float32)}
            opt = Optimizer(
                dht=dht, run_id="dpu_test", target_batch_size=64,
                params=params, optimizer=optax.sgd(0.3),
                batch_size_per_step=16, matchmaking_time=1.5, averaging_timeout=30,
                average_state_every=1, target_group_size=2,
                delay_optimizer_step=True, delta_rule_averaging=True,
                tracker_opts=dict(min_refresh_period=0.3, default_refresh_period=0.5),
            )
            rng_local = np.random.RandomState(index)
            first_loss = last_loss = None
            for _ in range(80):
                if opt.local_epoch >= 4:
                    break
                idx = rng_local.choice(len(features), 16)
                loss, grads = loss_and_grad(opt.params, features[idx], targets[idx])
                first_loss = first_loss if first_loss is not None else float(loss)
                last_loss = float(loss)
                opt.step(grads)
                if opt._pending_update is not None and not opt._pending_update.done():
                    overlap_observed.set()  # training continued during an in-flight round
                time.sleep(0.25)
            results[index] = (first_loss, last_loss, opt.local_epoch)
            opt.shutdown()
        except Exception as e:
            import traceback

            errors.append((index, e, traceback.format_exc()))

    threads = [threading.Thread(target=run_peer, args=(i, d)) for i, d in enumerate(dhts)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    try:
        assert not errors, f"peer failures: {errors}"
        assert len(results) == 2
        assert overlap_observed.is_set(), "no step() returned during an in-flight transition"
        for index, (first_loss, last_loss, epoch) in results.items():
            assert epoch >= 2, f"peer {index} stuck at epoch {epoch}"
            assert last_loss < first_loss / 5, (
                f"peer {index}: loss {first_loss:.4f} -> {last_loss:.4f} did not converge"
            )
    finally:
        for dht in dhts:
            dht.shutdown()


def test_delta_rule_preserves_concurrent_steps():
    """Deterministic delta-rule check: an optimizer step applied WHILE the averaging
    round is in flight must survive (result = current + average − snapshot)."""
    dht = DHT(start=True)
    try:
        params = {"w": jnp.full((4,), 10.0, jnp.float32)}
        averager = TrainingStateAverager(
            dht=dht, optimizer=optax.sgd(1.0), params=params, prefix="deltarule",
            start=True, delta_rule_averaging=True, average_opt_statistics=False,
        )

        fake_average = np.full((4,), 8.0, np.float32)  # pretend the group averaged to 8

        def fake_step(self_unused=None, timeout=None, wait=True, **kwargs):
            # concurrent local update lands mid-round: params 10 -> 6 (sgd lr=1, grad=4)
            averager.apply_optimizer_step({"w": jnp.full((4,), 4.0, jnp.float32)})
            with averager.get_tensors() as tensors:
                tensors[0][...] = fake_average
            return {}

        averager.step = fake_step
        assert averager.do_averaging_round(timeout=5)
        # delta rule: 6 + (8 − 10) = 4; plain overwrite would clobber the local step to 8
        np.testing.assert_allclose(np.asarray(averager.params["w"]), 4.0, atol=1e-6)
        averager.shutdown()
    finally:
        dht.shutdown()


def test_aux_peer_schema_bootstrap():
    """An auxiliary peer with ZERO model knowledge learns the gradient schema from
    the swarm (VERDICT r1 item 7)."""
    dhts = launch_dht_swarm(2)
    worker = aux = None
    try:
        params = {"w": jnp.zeros((6, 3), jnp.float32), "b": jnp.zeros(3, jnp.float32)}
        worker = Optimizer(
            dht=dhts[0], run_id="auxboot", target_batch_size=64,
            params=params, optimizer=optax.sgd(0.1), batch_size_per_step=16,
            matchmaking_time=1.0,
        )
        aux = Optimizer(
            dht=dhts[1], run_id="auxboot", target_batch_size=64,
            auxiliary=True, matchmaking_time=1.0, load_state_timeout=60,
        )
        assert aux.grad_averager is not None
        with aux.grad_averager.get_tensors() as tensors:
            shapes = sorted(tuple(t.shape) for t in tensors)
        assert shapes == sorted([(6, 3), (3,)])
        # matching schema hash means the aux peer can actually join groups
        assert aux.grad_averager.schema_hash == worker.grad_averager.schema_hash
    finally:
        for opt in (aux, worker):
            if opt is not None:
                opt.shutdown()
        for dht in dhts:
            dht.shutdown()


def test_state_dict_roundtrip_with_schedule_replay():
    """Checkpoint embeds the epoch; restoring replays optax step counters so LR
    schedules resume correctly (VERDICT r1 item 8)."""
    dht = DHT(start=True)
    try:
        schedule = optax.linear_schedule(0.0, 1.0, transition_steps=10)
        make_opt = lambda: optax.chain(optax.scale_by_adam(), optax.scale_by_schedule(schedule))
        params = {"w": jnp.ones((5,), jnp.float32)}

        source = Optimizer(
            dht=dht, run_id="ckpt_src", target_batch_size=64,
            params=params, optimizer=make_opt(), batch_size_per_step=16,
        )
        for _ in range(3):
            source.state_averager.apply_optimizer_step({"w": jnp.full((5,), 0.1, jnp.float32)})
        source.state_averager.local_epoch = 3
        checkpoint = source.state_dict()
        assert checkpoint["epoch"] == 3

        restored = Optimizer(
            dht=dht, run_id="ckpt_dst", target_batch_size=64,
            params=params, optimizer=make_opt(), batch_size_per_step=16,
        )
        restored.load_state_dict(checkpoint)
        assert restored.local_epoch == 3
        for mine, theirs in zip(
            restored.state_averager._host_state_tensors(),
            source.state_averager._host_state_tensors(),
        ):
            np.testing.assert_allclose(mine, theirs, atol=1e-6)
        # optax step counters were fast-forwarded to the epoch
        counts = [
            np.asarray(leaf)
            for path, leaf in jax.tree_util.tree_flatten_with_path(restored.state_averager.opt_state)[0]
            if path and getattr(path[-1], "name", None) == "count"
        ]
        assert counts and all(c == 3 for c in counts)
        source.shutdown()
        restored.shutdown()
    finally:
        dht.shutdown()


def test_one_epoch_grace_reload_rule():
    """Peers trailing by exactly one epoch must NOT redownload state — in EVERY
    mode (reference optimizer.py:654-672: the first peer to see enough samples
    transitions and restarts the count, so global == local + 1 is normal network
    asynchrony and the tracker reports the trailing peer ready to transition
    itself). Two or more epochs behind must reload."""
    dht = DHT(start=True)
    opt = None
    try:
        params = {"w": jnp.zeros((2,), jnp.float32)}
        opt = Optimizer(
            dht=dht, run_id="grace", target_batch_size=64,
            params=params, optimizer=optax.sgd(0.1), delay_optimizer_step=True,
        )
        opt.tracker.shutdown()
        opt.tracker = SimpleNamespace(global_epoch=1, shutdown=lambda: None)
        assert opt.local_epoch == 0
        assert not opt._should_load_state_from_peers()  # one behind: grace
        opt.tracker.global_epoch = 2
        assert opt._should_load_state_from_peers()  # two behind: reload
        # an in-flight background transition suppresses reload entirely
        opt._pending_update = SimpleNamespace(done=lambda: False)
        assert not opt._should_load_state_from_peers()
        opt._pending_update = None
        # non-DPU peers get the SAME one-epoch grace (r5 reference-parity fix:
        # the old strict rule made sync peers discard progress and download
        # state whenever a groupmate merely transitioned first)
        opt.delay_optimizer_step = False
        opt.tracker.global_epoch = 1
        assert not opt._should_load_state_from_peers()
        opt.tracker.global_epoch = 2
        assert opt._should_load_state_from_peers()
    finally:
        if opt is not None:
            opt.shutdown()
        dht.shutdown()


def test_local_updates_with_delayed_state_averaging():
    """The canonical local-SGD combination: use_local_updates + delay_state_averaging
    + delta_rule_averaging. State rounds run on the background thread while local
    steps continue; peers converge and stay in sync."""
    features, targets, loss_and_grad = _toy_problem(seed=4)
    dhts = launch_dht_swarm(2)
    results, errors = {}, []

    def run_peer(index: int, dht: DHT):
        try:
            params = {"w": jnp.zeros(8, jnp.float32)}
            opt = Optimizer(
                dht=dht, run_id="localsgd", target_batch_size=64,
                params=params, optimizer=optax.sgd(0.2),
                batch_size_per_step=16, matchmaking_time=1.5, averaging_timeout=30,
                average_state_every=1, target_group_size=2,
                use_local_updates=True, delay_state_averaging=True, delta_rule_averaging=True,
                tracker_opts=dict(min_refresh_period=0.3, default_refresh_period=0.5),
            )
            rng_local = np.random.RandomState(index)
            first_loss = last_loss = None
            for _ in range(80):
                if opt.local_epoch >= 4:
                    break
                idx = rng_local.choice(len(features), 16)
                loss, grads = loss_and_grad(opt.params, features[idx], targets[idx])
                first_loss = first_loss if first_loss is not None else float(loss)
                last_loss = float(loss)
                opt.step(grads)
                time.sleep(0.25)
            results[index] = (first_loss, last_loss, opt.local_epoch, np.asarray(opt.params["w"]))
            opt.shutdown()
        except Exception:
            import traceback

            errors.append((index, traceback.format_exc()))

    threads = [threading.Thread(target=run_peer, args=(i, d)) for i, d in enumerate(dhts)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    try:
        assert not errors, f"peer failures: {errors}"
        assert len(results) == 2
        for index, (first_loss, last_loss, epoch, _w) in results.items():
            assert epoch >= 2, f"peer {index} stuck at epoch {epoch}"
            assert last_loss < first_loss / 5, (
                f"peer {index}: loss {first_loss:.4f} -> {last_loss:.4f} did not converge"
            )
        w0, w1 = results[0][3], results[1][3]
        assert np.allclose(w0, w1, atol=0.25), f"peers diverged: {np.abs(w0 - w1).max()}"
    finally:
        for dht in dhts:
            dht.shutdown()


def test_powersgd_with_dpu_convergence():
    """The recipe's two throughput flags COMBINED: PowerSGD low-rank gradient
    compression inside Delayed Parameter Updates — compressed chained-phase
    averaging rounds run on the background thread while training continues."""
    from hivemind_tpu.optim import PowerSGDGradientAverager

    features, targets, loss_and_grad = _toy_problem()
    dhts = launch_dht_swarm(2)
    results, errors = {}, []

    def run_peer(index: int, dht: DHT):
        try:
            # w as a matrix so PowerSGD actually compresses (vectors pass raw)
            params = {"w": jnp.zeros((8, 1), jnp.float32)}
            opt = Optimizer(
                dht=dht, run_id="psgd_dpu_test", target_batch_size=64,
                params=params, optimizer=optax.sgd(0.3),
                batch_size_per_step=16, matchmaking_time=1.5, averaging_timeout=30,
                average_state_every=1, target_group_size=2,
                delay_optimizer_step=True, delta_rule_averaging=True,
                grad_averager_factory=PowerSGDGradientAverager,
                grad_averager_opts={"averager_rank": 4},
                tracker_opts=dict(min_refresh_period=0.3, default_refresh_period=0.5),
            )
            rng_local = np.random.RandomState(index)
            first_loss = last_loss = None
            for _ in range(80):
                if opt.local_epoch >= 4:
                    break
                idx = rng_local.choice(len(features), 16)
                loss, grads = loss_and_grad(
                    {"w": opt.params["w"][:, 0]}, features[idx], targets[idx]
                )
                first_loss = first_loss if first_loss is not None else float(loss)
                last_loss = float(loss)
                opt.step({"w": grads["w"][:, None]})
                time.sleep(0.25)
            results[index] = (first_loss, last_loss, opt.local_epoch)
            opt.shutdown()
        except Exception as e:
            import traceback

            errors.append((index, e, traceback.format_exc()))

    threads = [threading.Thread(target=run_peer, args=(i, d)) for i, d in enumerate(dhts)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    try:
        assert not errors, f"peer failures: {errors}"
        assert len(results) == 2
        for index, (first_loss, last_loss, epoch) in results.items():
            assert epoch >= 2, f"peer {index} stuck at epoch {epoch}"
            assert last_loss < first_loss / 5, (
                f"peer {index}: loss {first_loss:.4f} -> {last_loss:.4f} did not converge"
            )
    finally:
        for dht in dhts:
            dht.shutdown()
