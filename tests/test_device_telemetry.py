"""Device-side observability (ISSUE 19): compile tracking via tracked_jit and
recompile storms, watchdog-sampled device memory, the comm/compute step
timeline with overlap efficiency, snapshot/spool integration, and the
hivemind-top device board."""

import threading
import time
from types import SimpleNamespace

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from hivemind_tpu.optim import Optimizer
from hivemind_tpu.telemetry import watchdog as telemetry_watchdog
from hivemind_tpu.telemetry.blackbox import BlackBox
from hivemind_tpu.telemetry.device import (
    COMPILE_TRACKER,
    MEMORY_MONITOR,
    STEP_TIMELINE,
    JitCompileTracker,
    add_device_listener,
    arm_device_telemetry,
    compact_device_snapshot,
    device_snapshot,
    device_telemetry_armed,
    record_transfer,
    remove_device_listener,
    reset_device_telemetry,
    span_lane,
    transfer_totals,
    _union_overlap,
)
from hivemind_tpu.telemetry.ledger import LEDGER
from hivemind_tpu.telemetry.monitor import _shrink_to_fit
from hivemind_tpu.utils.profiling import tracked_jit
from hivemind_tpu.utils.serializer import MSGPackSerializer

from swarm_utils import launch_dht_swarm


# ----------------------------------------------------------- compile tracking


def test_tracked_jit_counts_compiles_not_cache_hits():
    @tracked_jit(site="test.add_one")
    def add_one(x):
        return x + 1

    x = jnp.arange(8, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(add_one(x)), np.arange(8) + 1)
    assert COMPILE_TRACKER.counts().get("test.add_one") == 1

    add_one(x + 5)  # same abstract signature: cache hit, NOT a compile
    assert COMPILE_TRACKER.counts().get("test.add_one") == 1

    add_one(jnp.arange(16, dtype=jnp.float32))  # new shape: recompile
    assert COMPILE_TRACKER.counts().get("test.add_one") == 2

    summary = COMPILE_TRACKER.summary()
    site = summary["sites"]["test.add_one"]
    assert site["count"] == 2 and site["seconds"] >= 0.0
    assert "float32" in site["signature"]  # the shape detail lives here, not in labels
    assert summary["last"]["site"] == "test.add_one"


def test_forced_recompiles_detect_a_storm_once_per_window():
    tracker = JitCompileTracker(storm_threshold=3, storm_window_s=60.0)
    for _ in range(10):  # one churning site, well past the threshold
        tracker.record_compile("moe.forward", duration_s=0.01, signature="f32[?]")
    assert tracker.storm_count() == 1, "a storm fires once per window, not per compile"
    assert tracker.counts()["moe.forward"] == 10
    assert tracker.summary()["storms"] == 1


def test_jax_monitoring_events_accrue_but_never_storm():
    tracker = JitCompileTracker(storm_threshold=2, storm_window_s=60.0)
    for _ in range(8):
        tracker.record_jax_event("/jax/compilation/backend_compile_time", 0.005)
    assert tracker.counts() == {"jax": 8}
    assert tracker.storm_count() == 0, "unattributed backend events are storm-exempt"
    assert tracker.total() == 0, "steady-state mark counts tracked sites only"
    assert tracker.total(include_jax_events=True) == 8


def test_compile_records_reach_device_listeners():
    events = []

    def listener(kind, record):
        events.append((kind, record))

    add_device_listener(listener)
    try:
        COMPILE_TRACKER.record_compile("test.listener_site", duration_s=0.02)
    finally:
        remove_device_listener(listener)
    kinds = [k for k, _ in events]
    assert "compile" in kinds
    record = dict(events[kinds.index("compile")][1])
    assert record["site"] == "test.listener_site" and record["count"] == 1


# ------------------------------------------------------------- device memory


def test_watchdog_tick_samples_live_device_memory():
    # retain a live device array across the sample: jax.live_arrays() only
    # sees buffers that have not been GC'd
    retained = jnp.ones((64, 64), dtype=jnp.float32)
    retained.block_until_ready()
    arm_device_telemetry()
    try:
        assert device_telemetry_armed()
        telemetry_watchdog._run_tick_samplers()
        sample = MEMORY_MONITOR.last_sample
        assert sample is not None and sample["total_bytes"] >= retained.nbytes
        assert sample["buffers"] >= 1 and sample["devices"]
        entry = next(iter(sample["devices"].values()))
        assert entry["peak_bytes"] >= entry["bytes"] > 0
    finally:
        reset_device_telemetry()
    del retained


def test_memory_sampler_is_inert_without_jax_in_the_process():
    # the monitor reads sys.modules and must never import jax itself: a
    # process that has not touched jax pays nothing for the sampler
    assert MEMORY_MONITOR.sample(modules={}) is None
    assert MEMORY_MONITOR.last_sample is None


def test_leak_heuristic_fires_on_monotonic_growth_then_resets():
    leaks = []

    def listener(kind, record):
        if kind == "leak":
            leaks.append(record)

    add_device_listener(listener)
    try:
        growth = MEMORY_MONITOR.leak_min_growth // 4
        buffers = []
        for _ in range(MEMORY_MONITOR.leak_samples):
            buffers.append(jnp.ones(growth // 4, dtype=jnp.float32))  # 4 B/elem
            buffers[-1].block_until_ready()
            MEMORY_MONITOR.sample()
        assert MEMORY_MONITOR.leak_count() == 1, "strict growth across the window"
        assert leaks and leaks[0]["growth_bytes"] >= MEMORY_MONITOR.leak_min_growth
        # the trend restarts after firing: the very next sample cannot re-fire
        MEMORY_MONITOR.sample()
        assert MEMORY_MONITOR.leak_count() == 1
    finally:
        remove_device_listener(listener)
    del buffers


def test_record_transfer_accounts_both_directions():
    before = transfer_totals()
    record_transfer(1000, "host_to_device")
    record_transfer(250, "device_to_host")
    record_transfer(0, "host_to_device")  # no-op, not an error
    after = transfer_totals()
    assert after["host_to_device"] - before["host_to_device"] == 1000
    assert after["device_to_host"] - before["device_to_host"] == 250
    with pytest.raises(ValueError):
        record_transfer(1, "sideways")


# ------------------------------------------------------------- step timeline


def _span(name, start, end, peer="p0", **attrs):
    return SimpleNamespace(
        name=name, start=start, end=end, attributes={"peer": peer, **attrs}
    )


def test_union_overlap_merges_overlapping_intervals():
    assert _union_overlap([(0.0, 4.0), (2.0, 6.0)], 0.0, 10.0) == pytest.approx(6.0)
    assert _union_overlap([(12.0, 14.0)], 0.0, 10.0) == 0.0
    assert _union_overlap([], 0.0, 10.0) == 0.0


def test_overlap_efficiency_on_scripted_spans():
    timeline = STEP_TIMELINE
    # compute covers [0, 10]; a fully hidden round and a half-exposed one
    timeline.on_span(_span("optimizer.update", 0.0, 10.0))
    timeline.on_span(_span("allreduce.round", 2.0, 6.0))
    timeline.on_span(_span("allreduce.round", 8.0, 12.0))
    records = timeline.records()
    assert [r["overlap_ratio"] for r in records] == [1.0, 0.5]
    summary = timeline.overlap_summary()
    assert summary["rounds"] == 2
    assert summary["mean"] == pytest.approx(0.75)
    assert summary["last"] == 0.5
    # allreduce.round ratios stamp the round ledger's overlap rollup
    assert LEDGER is not None  # stamping is lazy; nothing to assert without records


def test_overlap_ignores_other_peers_compute():
    STEP_TIMELINE.on_span(_span("optimizer.update", 0.0, 10.0, peer="other"))
    STEP_TIMELINE.on_span(_span("allreduce.round", 2.0, 6.0, peer="victim"))
    assert STEP_TIMELINE.records()[-1]["overlap_ratio"] == 0.0


def test_step_records_carry_the_grad_ready_offset():
    from hivemind_tpu.telemetry.tracing import telemetry_time

    STEP_TIMELINE.note_grad_ready("p0")
    now = telemetry_time()
    STEP_TIMELINE.on_span(_span("optimizer.step", now - 1.0, now + 1.0, epoch=3))
    steps = STEP_TIMELINE.steps()
    assert steps[-1]["epoch"] == 3
    assert 0.0 <= steps[-1]["grad_ready_s"] <= 2.0


def test_span_lane_classification():
    assert span_lane("optimizer.update") == "compute"
    assert span_lane("allreduce.round") == "comm"
    assert span_lane("allreduce.peer_exchange") == "comm"  # child: comm LANE only
    assert span_lane("dht.store") is None


def test_two_peer_round_produces_overlap_records():
    """One real local-updates run: optimizer.update compute spans + the state
    averaging round's allreduce.round span land in the timeline, producing
    overlap records with sane ratios (the benchmark asserts nonzero-ness on
    its longer, steadier run)."""
    rng = np.random.RandomState(0)
    features = rng.randn(128, 4).astype(np.float32)
    targets = features @ rng.randn(4).astype(np.float32)

    dhts = launch_dht_swarm(2)
    errors = []

    def run_peer(index, dht):
        try:
            opt = Optimizer(
                dht=dht, run_id="overlap_test", target_batch_size=32,
                params={"w": jnp.zeros(4, jnp.float32)}, optimizer=optax.sgd(0.1),
                batch_size_per_step=16, matchmaking_time=1.0, averaging_timeout=30,
                average_state_every=1, target_group_size=2, verbose=False,
                use_local_updates=True, delay_state_averaging=True,
                tracker_opts=dict(min_refresh_period=0.3, default_refresh_period=0.5),
            )
            loss_grad = jax.jit(jax.value_and_grad(
                lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2)
            ))
            local = np.random.RandomState(index)
            for _ in range(40):
                if opt.local_epoch >= 2:
                    break
                idx = local.choice(len(features), 16)
                _, grads = loss_grad(opt.params, features[idx], targets[idx])
                opt.step(grads)
                time.sleep(0.2)
            opt.shutdown()
        except Exception as e:
            import traceback

            errors.append((index, e, traceback.format_exc()))

    threads = [threading.Thread(target=run_peer, args=(i, d)) for i, d in enumerate(dhts)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    try:
        assert not errors, f"peer failures: {errors}"
        steps = STEP_TIMELINE.steps()
        assert steps, "optimizer.step spans must close step records"
        summary = STEP_TIMELINE.overlap_summary()
        assert summary["rounds"] >= 1, "state averaging rounds must land in the timeline"
        assert all(0.0 <= r["overlap_ratio"] <= 1.0 for r in STEP_TIMELINE.records())
    finally:
        for dht in dhts:
            dht.shutdown()


# ------------------------------------------------- snapshot / spool / boards


def _fat_device_section():
    return {
        "compiles": {
            "total": 40, "seconds": 12.5, "storms": 1,
            "sites": {
                f"site.{i}": {"count": 4, "seconds": 1.0, "signature": "x" * 200}
                for i in range(16)
            },
            "last": {"site": "site.0", "count": 4, "dur_s": 0.5, "signature": "x" * 200},
        },
        "memory": {
            "devices": {
                f"cpu:{i}": {"bytes": 1 << 20, "buffers": 100, "peak_bytes": 1 << 21}
                for i in range(8)
            },
            "total_bytes": 8 << 20,
            "buffers": 800,
        },
        "transfer_bytes": {"host_to_device": 123456, "device_to_host": 654321},
        "overlap": {"rounds": 9, "last": 0.8, "mean": 0.7},
    }


def test_device_snapshot_is_empty_when_nothing_happened():
    reset_device_telemetry()
    assert device_snapshot() == {}


def test_device_snapshot_surfaces_activity():
    COMPILE_TRACKER.record_compile("test.site", duration_s=0.1)
    record_transfer(512, "host_to_device")
    snapshot = device_snapshot()
    assert snapshot["compiles"]["sites"]["test.site"]["count"] == 1
    assert snapshot["transfer_bytes"]["host_to_device"] >= 512


def test_shrink_to_fit_compacts_then_drops_the_device_section():
    device = _fat_device_section()
    base = {"time": 1.0, "peer": "p0", "metrics": {}}

    # generous budget: compaction suffices — headline numbers survive
    compact_budget = len(MSGPackSerializer.dumps(
        {**base, "device": compact_device_snapshot(device), "truncated": True}
    )) + 16
    shrunk = _shrink_to_fit({**base, "device": device}, max_bytes=compact_budget)
    assert shrunk["truncated"] is True
    assert shrunk["device"]["compiles"]["total"] == 40
    assert "sites" not in shrunk["device"]["compiles"]
    assert shrunk["device"]["memory"] == {"total_bytes": 8 << 20, "buffers": 800}
    assert shrunk["device"]["overlap"]["mean"] == 0.7
    assert len(MSGPackSerializer.dumps(shrunk)) <= compact_budget

    # brutal budget: the device section goes before the core health record
    tiny_budget = len(MSGPackSerializer.dumps({**base, "truncated": True})) + 8
    shrunk = _shrink_to_fit({**base, "device": device}, max_bytes=tiny_budget)
    assert "device" not in shrunk
    assert len(MSGPackSerializer.dumps(shrunk)) <= tiny_budget


def test_device_frames_spool_past_the_peer_filter_and_memory_is_throttled(tmp_path):
    from hivemind_tpu.hivemind_cli.run_blackbox import read_spool

    # peer_filter targets another peer: device telemetry is process-scoped
    # (one jit cache, one HBM pool), so device frames must bypass it
    box = BlackBox(tmp_path, peer_filter="someone_else", metrics_interval=None)
    try:
        COMPILE_TRACKER.record_compile("test.spooled", duration_s=0.05)
        memory_record = {"total_bytes": 1024, "buffers": 2, "devices": {}}
        box._on_device_record("memory", memory_record)
        box._on_device_record("memory", memory_record)  # inside the 5 s throttle
    finally:
        box.close()
    frames, _stats = read_spool(tmp_path)
    device_frames = [f for f in frames if f["k"] == "device"]
    kinds = [f["d"]["kind"] for f in device_frames]
    assert kinds.count("compile") == 1
    assert kinds.count("memory") == 1, "memory frames throttle to one per 5 s"
    compile_frame = next(f for f in device_frames if f["d"]["kind"] == "compile")
    assert compile_frame["d"]["site"] == "test.spooled"


def test_run_blackbox_aggregates_device_frames_into_postmortem_and_snapshot(tmp_path):
    from hivemind_tpu.hivemind_cli.run_blackbox import (
        load_spools,
        reconstruct_final_round,
        spool_snapshot,
    )
    from hivemind_tpu.hivemind_cli.run_top import render_device_board

    box = BlackBox(tmp_path, peer="p0", metrics_interval=None)
    try:
        COMPILE_TRACKER.record_compile("test.victim_site", duration_s=0.2)
        box._on_device_record(
            "memory", {"total_bytes": 4096, "buffers": 3, "devices": {}}
        )
        box._on_device_record("overlap", {"kind": "allreduce.round", "overlap_ratio": 0.6})
        box._on_device_record("storm", {"site": "test.victim_site", "count": 7})
    finally:
        box.close()

    spools = load_spools([tmp_path])
    frames = spools["p0"]["frames"]
    post = reconstruct_final_round(frames, spools["p0"]["stats"])
    assert post["device"]["compiles"]["total"] >= 1
    assert post["device"]["compiles"]["storms"] == 1
    assert post["device"]["last_compile"]["site"] == "test.victim_site"
    assert post["device"]["memory"]["total_bytes"] == 4096
    assert post["device"]["overlap"]["last"] == 0.6

    snapshot = spool_snapshot(spools["p0"])
    assert snapshot["device"]["compiles"]["total"] >= 1
    board = render_device_board({"p0": snapshot}, ansi=False)
    assert "p0" in board and "test.victim_site" in board


def test_device_board_renders_live_snapshot_shape():
    from hivemind_tpu.hivemind_cli.run_top import render_device_board

    records = {
        "peerA": {"device": _fat_device_section()},
        "peerB": {"device": {}},  # inactive peer: no row
        "peerC": {"device": {"compiles": "garbage"}},  # malformed: flagged row
    }
    board = render_device_board(records, ansi=False)
    assert "peerA" in board
    assert "peerB" not in board
    assert "malformed device section" in board
    assert "site.0" in board  # hot compile sites
    assert "recompile-storm" in board  # storms surface as alerts


def test_monitor_snapshot_includes_device_section_when_active():
    from hivemind_tpu.telemetry.monitor import build_peer_snapshot

    reset_device_telemetry()
    snapshot = build_peer_snapshot()
    assert "device" not in snapshot, "inactive device telemetry publishes nothing"

    COMPILE_TRACKER.record_compile("test.published", duration_s=0.01)
    snapshot = build_peer_snapshot()
    assert snapshot["device"]["compiles"]["sites"]["test.published"]["count"] == 1
