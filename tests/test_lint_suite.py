"""hivemind-lint (ISSUE 16): tier-1 wiring plus self-tests for the suite.

Three layers of coverage:

1. the real tree is CLEAN — zero unsuppressed findings, zero stale allowlist
   entries, whole 10-rule suite inside the tier-1 time budget;
2. every rule actually catches what it claims to catch (MUST-flag fixtures in
   ``tools/lint/fixtures/<rule>/flag.py``, each tied to a named historical bug
   class) and does not cry wolf on the approved pattern (``ok.py``);
3. the shared mechanics — ``# lint: allow(...)`` suppression, the
   ``single-writer`` alias, justification-required allowlists, stale-entry
   detection, CLI exit codes — behave as documented, plus the runtime side of
   the fire-and-forget story: ``spawn()`` logs and counts background failures.
"""

import asyncio
import json
import logging
import shutil
import sys
import textwrap
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT / "tools"))

from lint import cli  # noqa: E402
from lint.engine import LintContext, load_allowlist, run_rule, run_suite  # noqa: E402
from lint.rules import ALL_RULES, get_rule  # noqa: E402

FIXTURES = REPO_ROOT / "tools" / "lint" / "fixtures"

# suite budget from ISSUE 16 acceptance: the full suite must stay tier-1-cheap
SUITE_BUDGET_S = 15.0


# --------------------------------------------------------------- the real tree


def test_repo_tree_is_clean_and_fast():
    """The tier-1 gate: no unsuppressed finding, no stale allowlist entry."""
    suite = run_suite()
    problems = [f.render() for r in suite.results for f in r.violations]
    problems += [
        f"stale allowlist entry for {r.rule.name}: {key}"
        for r in suite.results
        for key in r.stale_allowlist
    ]
    assert not problems, "hivemind-lint is dirty:\n  " + "\n  ".join(problems)
    assert suite.duration_s < SUITE_BUDGET_S, (
        f"lint suite took {suite.duration_s:.1f}s — over the {SUITE_BUDGET_S:.0f}s "
        f"tier-1 budget; a rule regressed from AST-walk to something quadratic"
    )


def test_every_rule_names_its_bug_class():
    """Each rule documents the historical defect it exists to prevent."""
    for rule_cls in ALL_RULES:
        assert rule_cls.name and rule_cls.title, rule_cls
        assert len(rule_cls.rationale) > 40, f"{rule_cls.name}: rationale missing"


# ------------------------------------------------------------- fixture pairs

# (rule, where the fixture must live to be in the rule's scope, kinds flag.py
#  must produce). hotpath-copies scans an explicit file list, so its fixture
#  impersonates p2p/mux.py; tree-scoped rules get a file in a scanned subtree.
_AST_CASES = [
    ("adhoc-retries", "utils/mod.py", {"swallow", "retry-loop"}),
    ("blocking-in-async", "p2p/mod.py", {"time-sleep", "blocking-io", "sync-socket"}),
    ("hotpath-copies", "p2p/mux.py", {"bytes-concat", "copy-astype"}),
    ("jit-in-hot-path", "moe/mod.py", {"inline-jit"}),
    ("async-shared-state", "averaging/mod.py", {"interleaved:followers", "interleaved:pending"}),
    ("fire-and-forget", "p2p/mod.py", {"dropped-task"}),
    ("missing-deadline", "moe/mod.py", {"no-deadline"}),
]


def _fixture_ctx(tmp_path: Path, rule_name: str, variant: str, dest: str) -> LintContext:
    package = tmp_path / "hivemind_tpu"
    target = package / dest
    target.parent.mkdir(parents=True, exist_ok=True)
    shutil.copyfile(FIXTURES / rule_name / f"{variant}.py", target)
    return LintContext(repo_root=tmp_path, package_root=package)


@pytest.mark.parametrize("rule_name,dest,expected", _AST_CASES, ids=[c[0] for c in _AST_CASES])
def test_rule_flags_its_bug_class(tmp_path, rule_name, dest, expected):
    ctx = _fixture_ctx(tmp_path, rule_name, "flag", dest)
    findings = get_rule(rule_name)().run(ctx)
    assert {f.kind for f in findings} == expected, [f.render() for f in findings]


@pytest.mark.parametrize("rule_name,dest,expected", _AST_CASES, ids=[c[0] for c in _AST_CASES])
def test_rule_passes_the_approved_pattern(tmp_path, rule_name, dest, expected):
    ctx = _fixture_ctx(tmp_path, rule_name, "ok", dest)
    findings = get_rule(rule_name)().run(ctx)
    assert findings == [], [f.render() for f in findings]


def test_scoping_fixture_outside_rule_scope_is_ignored(tmp_path):
    """hotpath-copies scans ONLY its named hot-path files: the same concat in
    an unlisted module must not fire."""
    ctx = _fixture_ctx(tmp_path, "hotpath-copies", "flag", "p2p/other.py")
    assert get_rule("hotpath-copies")().run(ctx) == []


# --------------------------------------------------- project-rule fixture trees

# The cross-file rules (metric-docs, chaos-coverage, wire-drift) check-in whole
# mini-repo TREES under fixtures/<rule>/{flag,ok}/ — the flag tree MUST produce
# exactly these kinds, the ok tree MUST stay silent (ISSUE 17 satellite).
_TREE_CASES = [
    ("metric-docs", {"undocumented-metric", "dynamic-metric-name"}),
    ("chaos-coverage", {
        "undocumented:net.ghost",  # declared, not in the doc
        "unexercised:net.ghost",  # declared, not in DEFAULT_SCHEDULE
        "phantom:net.typo",  # soaked, not declared
        "stale-doc:net.removed",  # catalog row for a deleted point
        "unknown:net.bogus",  # inject() literal for an undeclared point
    }),
    ("wire-drift", {"tag-drift", "tag-unverifiable"}),
]


def _tree_ctx(tmp_path: Path, rule_name: str, variant: str) -> LintContext:
    root = tmp_path / variant
    shutil.copytree(FIXTURES / rule_name / variant, root)
    return LintContext(repo_root=root, package_root=root / "hivemind_tpu")


@pytest.mark.parametrize("rule_name,expected", _TREE_CASES, ids=[c[0] for c in _TREE_CASES])
def test_project_rule_flags_its_fixture_tree(tmp_path, rule_name, expected):
    findings, _warnings = get_rule(rule_name)().run(_tree_ctx(tmp_path, rule_name, "flag"))
    assert {f.kind for f in findings} == expected, [f.render() for f in findings]


@pytest.mark.parametrize("rule_name,expected", _TREE_CASES, ids=[c[0] for c in _TREE_CASES])
def test_project_rule_passes_its_synced_tree(tmp_path, rule_name, expected):
    findings, _warnings = get_rule(rule_name)().run(_tree_ctx(tmp_path, rule_name, "ok"))
    assert findings == [], [f.render() for f in findings]


def test_every_rule_ships_must_flag_and_must_pass_fixtures():
    """All ten rules carry checked-in fixtures: file pairs for the AST rules,
    mini-repo trees for the cross-file project rules."""
    covered = {case[0] for case in _AST_CASES} | {case[0] for case in _TREE_CASES}
    assert covered == {rule_cls.name for rule_cls in ALL_RULES}
    for rule_cls in ALL_RULES:
        fixture_dir = FIXTURES / rule_cls.name
        assert (fixture_dir / "flag.py").is_file() or (fixture_dir / "flag").is_dir(), (
            f"{rule_cls.name}: no MUST-flag fixture"
        )
        assert (fixture_dir / "ok.py").is_file() or (fixture_dir / "ok").is_dir(), (
            f"{rule_cls.name}: no MUST-pass fixture"
        )


# ----------------------------------------------------------- project rules


def _project_ctx(tmp_path: Path) -> LintContext:
    package = tmp_path / "hivemind_tpu"
    package.mkdir(parents=True, exist_ok=True)
    return LintContext(repo_root=tmp_path, package_root=package)


def test_metric_docs_catches_drift_both_ways(tmp_path):
    ctx = _project_ctx(tmp_path)
    (tmp_path / "hivemind_tpu" / "mod.py").write_text(textwrap.dedent("""\
        A = REGISTRY.counter("hivemind_documented_total", "d", ())
        B = REGISTRY.counter("hivemind_phantom_total", "d", ())
        name = "computed"
        C = REGISTRY.gauge(name, "d")
    """))
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "| `hivemind_documented_total` | counter | — | fine |\n"
        "| `hivemind_stale_total` | counter | — | registered nowhere |\n"
    )
    findings, warnings = get_rule("metric-docs")().run(ctx)
    by_kind = {f.kind: f for f in findings}
    assert set(by_kind) == {"undocumented-metric", "dynamic-metric-name"}
    assert "hivemind_phantom_total" in by_kind["undocumented-metric"].message
    assert any("hivemind_stale_total" in w for w in warnings), warnings


def test_chaos_coverage_catches_every_drift_axis(tmp_path):
    ctx = _project_ctx(tmp_path)
    package = tmp_path / "hivemind_tpu"
    (package / "resilience").mkdir()
    (package / "hivemind_cli").mkdir()
    (package / "resilience" / "chaos.py").write_text(
        'INJECTION_POINTS = (\n    "dht.rpc_drop",\n    "net.stall",\n    "net.ghost",\n)\n'
    )
    (package / "hivemind_cli" / "run_chaos_soak.py").write_text(
        "DEFAULT_SCHEDULE = (\n"
        '    ("dht.rpc_drop", 0.1),\n'
        '    ("net.stall", 0.1),\n'
        '    ("net.typo", 0.1),\n'
        ")\n"
    )
    (package / "caller.py").write_text('CHAOS.inject("net.bogus")\n')
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "resilience.md").write_text(
        "prose may mention `net.anything` without being a catalog row\n"
        "| `dht.rpc_drop` | drops RPCs |\n"
        "| `net.stall` | stalls links |\n"
        "| `net.removed` | point deleted from the engine |\n"
    )
    findings, _warnings = get_rule("chaos-coverage")().run(ctx)
    assert {f.kind for f in findings} == {
        "undocumented:net.ghost",  # declared, not in the doc
        "unexercised:net.ghost",  # declared, not in DEFAULT_SCHEDULE
        "phantom:net.typo",  # soaked, not declared
        "stale-doc:net.removed",  # catalog row for a deleted point
        "unknown:net.bogus",  # inject() literal for an undeclared point
    }, [f.render() for f in findings]


def _wire_tree(tmp_path: Path) -> LintContext:
    """A tmp repo with the REAL proto modules + serialization + regenerator."""
    package = tmp_path / "hivemind_tpu"
    for rel in (
        "proto/averaging_pb2.py",
        "proto/dht_pb2.py",
        "proto/runtime_pb2.py",
        "proto/test_pb2.py",
        "compression/serialization.py",
    ):
        dst = package / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copyfile(REPO_ROOT / "hivemind_tpu" / rel, dst)
    (tmp_path / "tools").mkdir()
    shutil.copyfile(REPO_ROOT / "tools" / "regen_proto.py", tmp_path / "tools" / "regen_proto.py")
    return LintContext(repo_root=tmp_path, package_root=package)


def test_wire_drift_clean_on_pristine_copies(tmp_path):
    ctx = _wire_tree(tmp_path)
    findings, _warnings = get_rule("wire-drift")().run(ctx)
    assert findings == [], [f.render() for f in findings]


def test_wire_drift_catches_hand_edited_pb2(tmp_path):
    ctx = _wire_tree(tmp_path)
    pb2 = tmp_path / "hivemind_tpu" / "proto" / "averaging_pb2.py"
    pb2.write_text(pb2.read_text() + "\n# a hand edit the regenerator would erase\n")
    findings, _warnings = get_rule("wire-drift")().run(ctx)
    assert {f.kind for f in findings} == {"regen-drift"}, [f.render() for f in findings]


def test_wire_drift_catches_renumbered_tag(tmp_path):
    ctx = _wire_tree(tmp_path)
    ser = tmp_path / "hivemind_tpu" / "compression" / "serialization.py"
    source = ser.read_text()
    assert "# ExpertRequest.metadata = 3" in source
    ser.write_text(source.replace("# ExpertRequest.metadata = 3", "# ExpertRequest.metadata = 9"))
    findings, _warnings = get_rule("wire-drift")().run(ctx)
    assert {f.kind for f in findings} == {"tag-drift"}, [f.render() for f in findings]
    assert any("_REQUEST_METADATA_TAG" in f.message for f in findings)


def test_wire_drift_catches_unannotated_tag(tmp_path):
    ctx = _wire_tree(tmp_path)
    ser = tmp_path / "hivemind_tpu" / "compression" / "serialization.py"
    ser.write_text(
        ser.read_text().replace(
            '_REQUEST_UID_TAG = b"\\x0a"  # ExpertRequest.uid = 1',
            '_REQUEST_UID_TAG = b"\\x0a"',
        )
    )
    findings, _warnings = get_rule("wire-drift")().run(ctx)
    assert {f.kind for f in findings} == {"tag-unverifiable"}, [f.render() for f in findings]


# ------------------------------------------------- suppression + allowlists


def _dropped_task_ctx(tmp_path: Path, body: str) -> LintContext:
    package = tmp_path / "hivemind_tpu"
    package.mkdir(parents=True, exist_ok=True)
    (package / "mod.py").write_text(textwrap.dedent(body))
    return LintContext(repo_root=tmp_path, package_root=package)


def test_line_suppression_moves_finding_to_suppressed(tmp_path):
    ctx = _dropped_task_ctx(tmp_path, """\
        import asyncio


        async def go(coro):
            asyncio.create_task(coro)  # lint: allow(fire-and-forget) — test fixture
    """)
    result = run_rule(get_rule("fire-and-forget")(), ctx, allowlist_dir=tmp_path / "nowhere")
    assert not result.violations
    assert len(result.suppressed) == 1


def test_block_suppression_on_def_line_covers_the_body(tmp_path):
    ctx = _dropped_task_ctx(tmp_path, """\
        import asyncio


        async def go(coro):  # lint: allow(fire-and-forget) — whole body reviewed
            asyncio.create_task(coro)
            asyncio.ensure_future(coro)
    """)
    result = run_rule(get_rule("fire-and-forget")(), ctx, allowlist_dir=tmp_path / "nowhere")
    assert not result.violations
    assert len(result.suppressed) == 2


def test_single_writer_alias_suppresses_async_shared_state(tmp_path):
    package = tmp_path / "hivemind_tpu"
    (package / "p2p").mkdir(parents=True)
    (package / "p2p" / "mod.py").write_text(textwrap.dedent("""\
        class Pump:
            async def drain(self, queue):
                while True:
                    item = await queue.get()
                    self.pending.append(item)  # lint: single-writer — sole consumer
    """))
    ctx = LintContext(repo_root=tmp_path, package_root=package)
    result = run_rule(
        get_rule("async-shared-state")(), ctx, allowlist_dir=tmp_path / "nowhere"
    )
    assert not result.violations
    assert len(result.suppressed) == 1


def test_allowlist_requires_a_justification(tmp_path):
    allowlists = tmp_path / "allowlists"
    allowlists.mkdir()
    (allowlists / "fire-and-forget.conf").write_text(
        "hivemind_tpu/mod.py:go:dropped-task\n"
    )
    with pytest.raises(ValueError, match="justification"):
        load_allowlist("fire-and-forget", allowlists)


def test_allowlist_matches_by_key_and_reports_stale_entries(tmp_path):
    ctx = _dropped_task_ctx(tmp_path, """\
        import asyncio


        async def go(coro):
            asyncio.create_task(coro)
    """)
    allowlists = tmp_path / "allowlists"
    allowlists.mkdir()
    (allowlists / "fire-and-forget.conf").write_text(
        "hivemind_tpu/mod.py:go:dropped-task  reviewed: fixture\n"
        "hivemind_tpu/gone.py:old:dropped-task  the finding this covered is gone\n"
    )
    result = run_rule(get_rule("fire-and-forget")(), ctx, allowlist_dir=allowlists)
    assert not result.violations
    assert len(result.allowlisted) == 1
    assert result.stale_allowlist == ["hivemind_tpu/gone.py:old:dropped-task"]


def test_real_allowlists_all_carry_justifications():
    for conf in sorted((REPO_ROOT / "tools" / "lint" / "allowlists").glob("*.conf")):
        entries = load_allowlist(conf.stem)
        for entry in entries.values():
            assert len(entry.justification) > 10, f"{conf.name}: {entry.key}"


# ------------------------------------------------------------------- the CLI


def test_cli_exits_nonzero_and_emits_json_on_violation(tmp_path, capsys):
    package = tmp_path / "hivemind_tpu"
    package.mkdir()
    (package / "mod.py").write_text(
        "import asyncio\n\n\nasync def go(coro):\n    asyncio.create_task(coro)\n"
    )
    rc = cli.main(["--root", str(tmp_path), "--rule", "fire-and-forget", "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert payload["ok"] is False
    assert payload["total_violations"] == 1
    finding = payload["rules"]["fire-and-forget"]["findings"][0]
    assert finding["kind"] == "dropped-task"
    assert finding["qualname"] == "go"


def test_cli_clean_tree_exits_zero(tmp_path, capsys):
    package = tmp_path / "hivemind_tpu"
    package.mkdir()
    (package / "mod.py").write_text("x = 1\n")
    rc = cli.main(["--root", str(tmp_path), "--rule", "fire-and-forget"])
    assert rc == 0
    assert "clean" in capsys.readouterr().out


def test_cli_lists_all_ten_rules(capsys):
    assert cli.main(["--list-rules"]) == 0
    listed = [line.split()[0] for line in capsys.readouterr().out.splitlines() if line]
    assert listed == [rule_cls.name for rule_cls in ALL_RULES]
    assert len(listed) == 10


def test_cli_rejects_unknown_rule():
    assert cli.main(["--rule", "no-such-rule"]) == 2


# ----------------------------------------------- spawn(): the runtime half


class _ListHandler(logging.Handler):
    def __init__(self):
        super().__init__()
        self.records = []

    def emit(self, record):
        self.records.append(record)


async def test_spawn_logs_and_counts_background_failures():
    """The fire-and-forget rule forces tasks through spawn(); spawn() must hold
    up its end — failures are logged AND counted, never silently retrieved."""
    from hivemind_tpu.telemetry.registry import REGISTRY
    from hivemind_tpu.utils.asyncio_utils import _background_tasks, spawn

    counter = REGISTRY.counter(
        "hivemind_background_task_errors_total", "", ("site",)
    )
    before = counter.value(site="test.spawn_failure")
    # the project logger does not propagate to the root logger caplog hooks,
    # so listen on the module logger directly
    handler = _ListHandler()
    logging.getLogger("hivemind_tpu.utils.asyncio_utils").addHandler(handler)

    async def boom():
        raise RuntimeError("fixture failure")

    try:
        task = spawn(boom(), name="test.spawn_failure")
        assert task in _background_tasks  # strong ref: not GC-collectable mid-flight
        with pytest.raises(RuntimeError):
            await task
        await asyncio.sleep(0)  # let the done-callback run
    finally:
        logging.getLogger("hivemind_tpu.utils.asyncio_utils").removeHandler(handler)

    assert task not in _background_tasks
    assert counter.value(site="test.spawn_failure") == before + 1
    messages = [record.getMessage() for record in handler.records]
    assert any(
        "test.spawn_failure" in message and "fixture failure" in message
        for message in messages
    ), messages


async def test_spawn_success_and_cancellation_are_not_counted():
    from hivemind_tpu.telemetry.registry import REGISTRY
    from hivemind_tpu.utils.asyncio_utils import spawn

    counter = REGISTRY.counter(
        "hivemind_background_task_errors_total", "", ("site",)
    )
    before = counter.value(site="test.spawn_clean")

    async def fine():
        return 7

    async def forever():
        await asyncio.Event().wait()

    ok_task = spawn(fine(), name="test.spawn_clean")
    assert await ok_task == 7
    cancelled = spawn(forever(), name="test.spawn_clean")
    cancelled.cancel()
    with pytest.raises(asyncio.CancelledError):
        await cancelled
    await asyncio.sleep(0)
    assert counter.value(site="test.spawn_clean") == before
