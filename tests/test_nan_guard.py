"""NaN-restore from periodic in-memory backups (VERDICT r2 next-round #9;
reference examples/albert/run_trainer.py:62-130): a poisoned step restores the
last healthy state instead of corrupting the run."""

import time

import numpy as np
import optax

from hivemind_tpu.dht import DHT
from hivemind_tpu.optim import NaNGuard, Optimizer


def _make_solo_optimizer(dht):
    params = {"w": np.ones(8, np.float32)}
    return Optimizer(
        dht=dht, run_id="nan_guard_test", target_batch_size=4,
        params=params, optimizer=optax.sgd(0.1), batch_size_per_step=4,
        matchmaking_time=0.5,
    )


def _drive_until_update(guard, grads, timeout=45.0):
    """Healthy steps until an epoch transition applies an optax update."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        params = guard.step(1.0, grads)
        if not np.allclose(np.asarray(params["w"]), 1.0):
            return params
        time.sleep(0.25)
    raise AssertionError("no epoch transition within the deadline")


def test_nan_restores_last_backup_and_drops_gradients():
    dht = DHT(start=True)
    opt = _make_solo_optimizer(dht)
    try:
        guard = NaNGuard(opt, backup_every=1)
        grads = {"w": np.full(8, 0.5, np.float32)}
        _drive_until_update(guard, grads)

        # the state right before the next healthy step is what its backup holds
        w_backup = np.asarray(opt.params["w"]).copy()
        epoch_backup = opt.local_epoch
        guard.step(1.0, grads)

        poisoned = {"w": np.full(8, 1e30, np.float32)}
        p = guard.step(float("nan"), poisoned)
        assert guard.restores == 1 and guard.skipped_steps == 1
        # poisoned gradients dropped AND state rolled back to the backup
        np.testing.assert_allclose(np.asarray(p["w"]), w_backup)
        assert opt.local_epoch == epoch_backup

        # +inf is caught the same way
        p = guard.step(float("inf"), poisoned)
        assert guard.restores == 2
        np.testing.assert_allclose(np.asarray(p["w"]), w_backup)
    finally:
        opt.shutdown()
        dht.shutdown()


def test_nan_before_any_backup_skips_but_survives():
    dht = DHT(start=True)
    opt = _make_solo_optimizer(dht)
    try:
        guard = NaNGuard(opt, backup_every=10)
        w0 = np.asarray(opt.params["w"]).copy()
        p = guard.step(float("nan"), {"w": np.full(8, 7.0, np.float32)})
        assert guard.restores == 0 and guard.skipped_steps == 1
        np.testing.assert_allclose(np.asarray(p["w"]), w0)  # untouched
    finally:
        opt.shutdown()
        dht.shutdown()


def test_check_grads_catches_finite_loss_nonfinite_grads():
    dht = DHT(start=True)
    opt = _make_solo_optimizer(dht)
    try:
        guard = NaNGuard(opt, backup_every=1, check_grads=True)
        w_backup = np.asarray(opt.params["w"]).copy()
        guard.step(1.0, {"w": np.full(8, 0.5, np.float32)})  # backup taken pre-step

        bad = {"w": np.array([1.0] * 7 + [np.nan], np.float32)}
        p = guard.step(0.9, bad)  # loss fine, one grad element NaN
        assert guard.skipped_steps == 1 and guard.restores == 1
        np.testing.assert_allclose(np.asarray(p["w"]), w_backup)
    finally:
        opt.shutdown()
        dht.shutdown()
