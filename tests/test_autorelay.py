"""Auto-relay via the DHT (VERDICT r2 next-round #6; reference use_auto_relay,
hivemind/p2p/p2p_daemon.py:114-137): a NATed peer with ZERO relay configuration
diagnoses itself via AutoNAT dial-back, discovers an advertised relay in the DHT,
registers there, publishes its circuits — and a public peer dials it purely by
peer id through the installed resolver."""

import asyncio
import subprocess
from pathlib import Path

import pytest

from hivemind_tpu.dht import DHT
from hivemind_tpu.p2p import P2P, AutoRelay, P2PContext, advertise_relay
from hivemind_tpu.p2p.autorelay import RELAY_DHT_KEY, RELAYED_PEER_PREFIX
from hivemind_tpu.proto import test_pb2

NATIVE_DIR = Path(__file__).parent.parent / "hivemind_tpu" / "native"
RELAY_BIN = NATIVE_DIR / "relay_daemon"


@pytest.fixture(scope="module")
def relay_daemon():
    if not RELAY_BIN.exists():
        subprocess.run(["make"], cwd=NATIVE_DIR, check=True, capture_output=True)
    proc = subprocess.Popen([str(RELAY_BIN), "0"], stdout=subprocess.PIPE, text=True)
    port = int(proc.stdout.readline().strip().rsplit(" ", 1)[-1])
    identity_line = proc.stdout.readline().strip()
    pubkey_hex = identity_line.rsplit(" ", 1)[-1] if "identity" in identity_line else ""
    yield port, pubkey_hex
    proc.kill()
    proc.wait()


def test_advertise_and_parse_relay_records(relay_daemon):
    port, pubkey_hex = relay_daemon
    dht = DHT(start=True)
    try:
        assert advertise_relay(dht, "127.0.0.1", port, pubkey_hex)
        record = dht.get(RELAY_DHT_KEY, latest=True)
        assert record is not None
        from hivemind_tpu.p2p.autorelay import _parse_relay_records

        relays = _parse_relay_records(record)
        assert ("127.0.0.1", port, pubkey_hex) in relays
    finally:
        dht.shutdown()


def test_natted_peer_zero_config_becomes_dialable(relay_daemon):
    port, pubkey_hex = relay_daemon

    async def scenario():
        # swarm bootstrap + a PUBLIC peer that serves the AutoNAT dial-back
        boot = DHT(start=True)
        maddrs = [str(m) for m in boot.get_visible_maddrs()]
        public_dht = DHT(initial_peers=maddrs, start=True)
        natted_dht = DHT(initial_peers=maddrs, start=True)

        # the relay operator advertises the daemon in the DHT — the ONLY place
        # relay coordinates exist in this test
        assert advertise_relay(boot, "127.0.0.1", port, pubkey_hex)

        public = await P2P.create()
        public_auto = await AutoRelay.create(public, public_dht)

        # "NATed": announces a dead port (like an unforwarded NAT mapping), so the
        # dial-back gets connection-refused and every direct dial fails fast
        import socket

        with socket.socket() as probe_sock:
            probe_sock.bind(("127.0.0.1", 0))
            dead_port = probe_sock.getsockname()[1]
        natted = await P2P.create(announce_port=dead_port, dial_timeout=1.0)

        async def echo(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
            return test_pb2.TestResponse(number=request.number + 1)

        await natted.add_protobuf_handler("echo", echo, test_pb2.TestRequest)

        # the NATed peer can reach the public peer (outbound works behind NAT)
        await natted.connect(public.get_visible_maddrs()[0])
        natted_auto = await AutoRelay.create(natted, natted_dht, probe_via=public.peer_id)

        # self-diagnosis found no reachable address → registered + published
        assert natted_auto.relay_clients, "NATed peer did not register at any relay"
        published = natted_dht.get(RELAYED_PEER_PREFIX + natted.peer_id.to_base58(), latest=True)
        assert published is not None and published.value

        # a fresh public client knows ONLY the peer id: resolver finds the circuit
        client = await P2P.create(dial_timeout=1.0)
        client_auto = await AutoRelay.create(client, public_dht)
        response = await client.call_protobuf_handler(
            natted.peer_id, "echo", test_pb2.TestRequest(number=41), test_pb2.TestResponse
        )
        assert response.number == 42

        # second call rides the established relayed connection
        response = await client.call_protobuf_handler(
            natted.peer_id, "echo", test_pb2.TestRequest(number=99), test_pb2.TestResponse
        )
        assert response.number == 100

        for auto in (client_auto, natted_auto, public_auto):
            await auto.close()
        for node in (client, natted, public):
            await node.shutdown()
        for dht in (public_dht, natted_dht, boot):
            dht.shutdown()

    asyncio.run(asyncio.wait_for(scenario(), timeout=120))


def test_maintenance_replaces_dead_relay(relay_daemon, tmp_path):
    """Failure recovery: the relay a NATed peer registered at dies; a maintenance
    pass detects the dropped control line and re-registers at another advertised
    relay, republishing circuits (reference auto-relay keeps peers dialable
    through relay churn)."""
    import subprocess

    port, pubkey_hex = relay_daemon

    async def scenario():
        # a second, short-lived relay the peer will register at FIRST
        victim = subprocess.Popen(
            [str(RELAY_BIN), "0"], stdout=subprocess.PIPE, text=True
        )
        victim_port = int(victim.stdout.readline().strip().rsplit(" ", 1)[-1])
        victim_key = victim.stdout.readline().strip().rsplit(" ", 1)[-1]
        try:
            dht = DHT(start=True)
            assert advertise_relay(dht, "127.0.0.1", victim_port, victim_key)
            natted = await P2P.create(dial_timeout=1.0)
            auto = await AutoRelay.create(natted, dht, max_relays=1, force_relay=True)
            assert set(auto.relay_clients) == {("127.0.0.1", victim_port)}

            # the registered relay dies; the survivor is advertised in its place
            victim.kill()
            victim.wait()
            assert advertise_relay(dht, "127.0.0.1", port, pubkey_hex)

            deadline = asyncio.get_event_loop().time() + 30
            while asyncio.get_event_loop().time() < deadline:
                await auto._maintenance_once()
                if ("127.0.0.1", port) in auto.relay_clients:
                    break
                await asyncio.sleep(0.5)
            assert set(auto.relay_clients) == {("127.0.0.1", port)}, auto.relay_clients

            published = dht.get(RELAYED_PEER_PREFIX + natted.peer_id.to_base58(), latest=True)
            assert published is not None
            endpoints = {c["endpoint"] for c in published.value}
            assert f"127.0.0.1:{port}" in endpoints

            await auto.close()
            await natted.shutdown()
            dht.shutdown()
        finally:
            if victim.poll() is None:
                victim.kill()
                victim.wait()

    asyncio.run(asyncio.wait_for(scenario(), timeout=120))
