"""Native relay daemon: a peer reachable only through the relay serves RPCs end-to-end
encrypted (scope: reference tests/test_relays.py circuit-relay reachability)."""

import asyncio
import os
import subprocess
import sys
from pathlib import Path

import pytest

from hivemind_tpu.p2p import P2P, P2PContext
from hivemind_tpu.p2p.relay import RelayClient
from hivemind_tpu.proto import test_pb2

NATIVE_DIR = Path(__file__).parent.parent / "hivemind_tpu" / "native"
RELAY_BIN = NATIVE_DIR / "relay_daemon"


@pytest.fixture(scope="module")
def relay_process():
    if not RELAY_BIN.exists():
        subprocess.run(["make"], cwd=NATIVE_DIR, check=True, capture_output=True)
    proc = subprocess.Popen(
        [str(RELAY_BIN), "0"], stdout=subprocess.PIPE, text=True
    )
    line = proc.stdout.readline()
    port = int(line.strip().rsplit(" ", 1)[-1])
    yield port
    proc.kill()
    proc.wait()


async def test_relayed_rpc_end_to_end(relay_process):
    port = relay_process
    # "firewalled" peer: registers at the relay, never shares its direct address
    server = await P2P.create()
    client = await P2P.create()

    async def triple(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
        return test_pb2.TestResponse(number=request.number * 3)

    await server.add_protobuf_handler("triple", triple, test_pb2.TestRequest)
    server_relay = await RelayClient.create(server, "127.0.0.1", port)

    client_relay = RelayClient(client, "127.0.0.1", port)
    peer = await client_relay.dial(server.peer_id)
    assert peer == server.peer_id

    response = await client.call_protobuf_handler(
        server.peer_id, "triple", test_pb2.TestRequest(number=14), test_pb2.TestResponse
    )
    assert response.number == 42

    # a second call reuses the spliced connection
    response = await client.call_protobuf_handler(
        server.peer_id, "triple", test_pb2.TestRequest(number=100), test_pb2.TestResponse
    )
    assert response.number == 300

    await server_relay.close()
    await client.shutdown()
    await server.shutdown()


async def test_relay_dial_unknown_peer(relay_process):
    port = relay_process
    client = await P2P.create()
    from hivemind_tpu.utils.crypto import Ed25519PrivateKey
    from hivemind_tpu.p2p.peer_id import PeerID

    ghost = PeerID.from_private_key(Ed25519PrivateKey())
    relay = RelayClient(client, "127.0.0.1", port)
    with pytest.raises(ConnectionError):
        await relay.dial(ghost)
    await client.shutdown()
