"""Native relay daemon: a peer reachable only through the relay serves RPCs end-to-end
encrypted (scope: reference tests/test_relays.py circuit-relay reachability)."""

import asyncio
import os
import subprocess
import sys
from pathlib import Path

import pytest

from hivemind_tpu.p2p import P2P, P2PContext
from hivemind_tpu.p2p.relay import RelayClient
from hivemind_tpu.proto import test_pb2

NATIVE_DIR = Path(__file__).parent.parent / "hivemind_tpu" / "native"
RELAY_BIN = NATIVE_DIR / "relay_daemon"


@pytest.fixture(scope="module")
def relay_process():
    if not RELAY_BIN.exists():
        subprocess.run(["make"], cwd=NATIVE_DIR, check=True, capture_output=True)
    proc = subprocess.Popen(
        [str(RELAY_BIN), "0"], stdout=subprocess.PIPE, text=True
    )
    line = proc.stdout.readline()
    port = int(line.strip().rsplit(" ", 1)[-1])
    yield port
    proc.kill()
    proc.wait()


@pytest.fixture(scope="module")
def relay_process_unix(tmp_path_factory):
    """A daemon ALSO listening on a 0600 AF_UNIX socket — the multi-user-safe
    trust boundary for the data-plane proxy's 'K' key handoff (advisor r4)."""
    if not RELAY_BIN.exists():
        subprocess.run(["make"], cwd=NATIVE_DIR, check=True, capture_output=True)
    socket_path = str(tmp_path_factory.mktemp("proxy") / "proxy.sock")
    proc = subprocess.Popen(
        [str(RELAY_BIN), "0", "", socket_path], stdout=subprocess.PIPE, text=True
    )
    line = proc.stdout.readline()
    assert "listening" in line, line
    yield socket_path
    proc.kill()
    proc.wait()


async def test_relayed_rpc_end_to_end(relay_process):
    port = relay_process
    # "firewalled" peer: registers at the relay, never shares its direct address
    server = await P2P.create()
    client = await P2P.create()

    async def triple(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
        return test_pb2.TestResponse(number=request.number * 3)

    await server.add_protobuf_handler("triple", triple, test_pb2.TestRequest)
    server_relay = await RelayClient.create(server, "127.0.0.1", port)

    client_relay = RelayClient(client, "127.0.0.1", port)
    peer = await client_relay.dial(server.peer_id)
    assert peer == server.peer_id

    response = await client.call_protobuf_handler(
        server.peer_id, "triple", test_pb2.TestRequest(number=14), test_pb2.TestResponse
    )
    assert response.number == 42

    # a second call reuses the spliced connection
    response = await client.call_protobuf_handler(
        server.peer_id, "triple", test_pb2.TestRequest(number=100), test_pb2.TestResponse
    )
    assert response.number == 300

    await server_relay.close()
    await client.shutdown()
    await server.shutdown()


async def test_relay_dial_unknown_peer(relay_process):
    port = relay_process
    client = await P2P.create()
    from hivemind_tpu.utils.crypto import Ed25519PrivateKey
    from hivemind_tpu.p2p.peer_id import PeerID

    ghost = PeerID.from_private_key(Ed25519PrivateKey())
    relay = RelayClient(client, "127.0.0.1", port)
    with pytest.raises(ConnectionError):
        await relay.dial(ghost)
    await client.shutdown()


async def _raw_conn(port):
    return await asyncio.open_connection("127.0.0.1", port)


async def test_relay_register_requires_key_proof(relay_process):
    """Registration is authenticated: the daemon challenges every REGISTER and only
    an Ed25519 signature from the key the peer_id hashes is accepted. An attacker
    without the key cannot register the victim's id; the owner CAN re-register and
    evicts its own stale control line (NAT-rebind reclamation)."""
    import base64

    from hivemind_tpu.p2p.peer_id import PeerID
    from hivemind_tpu.p2p.relay import RelayChannel, _recv_frame, _send_frame, register_control
    from hivemind_tpu.utils.crypto import Ed25519PrivateKey

    port = relay_process
    victim = Ed25519PrivateKey()
    victim_id = PeerID.from_private_key(victim).to_bytes()

    # capability probe: a daemon without system libcrypto degrades to legacy
    # unauthenticated registration ('O' straight away) — nothing to test there
    probe_r, probe_w = await _raw_conn(port)
    await _send_frame(probe_w, b"R" + victim_id)
    probe_response = await _recv_frame(probe_r)
    probe_w.close()
    if probe_response[:1] != b"C":
        pytest.skip("relay daemon running without libcrypto: legacy unauthenticated mode")

    r1, w1 = await _raw_conn(port)
    assert await register_control(RelayChannel(r1, w1), victim_id, victim) == b"O"

    # attacker presents the victim's (public) pubkey — hash matches — but can only
    # sign with its own key: the signature check must fail
    attacker = Ed25519PrivateKey()
    r2, w2 = await _raw_conn(port)
    await _send_frame(w2, b"R" + victim_id)
    challenge_frame = await _recv_frame(r2)
    assert challenge_frame[:1] == b"C" and len(challenge_frame) == 33
    message = b"hivemind-relay-register:" + challenge_frame[1:] + victim_id
    forged = base64.b64decode(attacker.sign(message))
    await _send_frame(w2, b"P" + victim.get_public_key().to_bytes() + forged)
    assert await _recv_frame(r2) == b"E"
    w2.close()

    # a pubkey whose hash doesn't match the claimed peer_id is also refused,
    # even with a valid signature from that key
    r3, w3 = await _raw_conn(port)
    await _send_frame(w3, b"R" + victim_id)
    challenge_frame = await _recv_frame(r3)
    message = b"hivemind-relay-register:" + challenge_frame[1:] + victim_id
    await _send_frame(
        w3, b"P" + attacker.get_public_key().to_bytes() + base64.b64decode(attacker.sign(message))
    )
    assert await _recv_frame(r3) == b"E"
    w3.close()

    # the owner reclaims: second registration with a valid proof evicts line 1
    r4, w4 = await _raw_conn(port)
    assert await register_control(RelayChannel(r4, w4), victim_id, victim) == b"O"
    assert await r1.read(100) == b""  # old control line was closed by the daemon
    w4.close()
    w1.close()


async def test_relay_encrypted_control_channel(relay_process):
    """The 'H' handshake gives an AEAD control channel bound to the relay's Ed25519
    identity: registration and a full relayed RPC work through it, a wrong pinned
    identity is refused before any control op, and TOFU pinning sticks."""
    from hivemind_tpu.p2p.relay import open_relay_channel

    port = relay_process
    channel = await open_relay_channel("127.0.0.1", port)
    if not channel.encrypted:
        pytest.skip("relay daemon running without libcrypto: no encrypted channel")
    relay_identity = channel.relay_pubkey
    assert len(relay_identity) == 32
    channel.close()

    # pinning the wrong identity must refuse the channel outright
    with pytest.raises(ConnectionError, match="identity mismatch"):
        await open_relay_channel("127.0.0.1", port, relay_pubkey=b"\x42" * 32)

    # end-to-end: server registers over the encrypted channel (pinned), client dials
    server = await P2P.create()
    client = await P2P.create()

    async def negate(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
        return test_pb2.TestResponse(number=-request.number)

    await server.add_protobuf_handler("negate", negate, test_pb2.TestRequest)
    server_relay = await RelayClient.create(
        server, "127.0.0.1", port, relay_pubkey=relay_identity
    )
    assert server_relay._control.encrypted

    client_relay = RelayClient(client, "127.0.0.1", port)
    await client_relay.dial(server.peer_id)
    assert client_relay.relay_pubkey == relay_identity  # TOFU pinned from the dial

    response = await client.call_protobuf_handler(
        server.peer_id, "negate", test_pb2.TestRequest(number=7), test_pb2.TestResponse
    )
    assert response.number == -7

    await server_relay.close()
    await client.shutdown()
    await server.shutdown()


async def test_p2p_create_relays_kwarg(relay_process):
    """P2P.create(relays=[...]) registers at the relay on startup (reference parity:
    use_relay/use_auto_relay) — a peer started this way is dialable through the
    relay with no direct address exchange."""
    port = relay_process
    server = await P2P.create(relays=[f"127.0.0.1:{port}"])
    assert len(server._relays) == 1
    client = await P2P.create()

    async def half(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
        return test_pb2.TestResponse(number=request.number // 2)

    await server.add_protobuf_handler("half", half, test_pb2.TestRequest)
    await RelayClient(client, "127.0.0.1", port).dial(server.peer_id)
    response = await client.call_protobuf_handler(
        server.peer_id, "half", test_pb2.TestRequest(number=84), test_pb2.TestResponse
    )
    assert response.number == 42
    await client.shutdown()
    await server.shutdown()


def test_relay_identity_persists_across_restarts(tmp_path):
    """With an identity file, the daemon announces the SAME Ed25519 identity after a
    restart, so client pins keep working."""
    identity_file = tmp_path / "relay.key"

    def start_and_read_identity():
        proc = subprocess.Popen(
            [str(RELAY_BIN), "0", str(identity_file)], stdout=subprocess.PIPE, text=True
        )
        try:
            proc.stdout.readline()  # listening line
            line = proc.stdout.readline().strip()
        finally:
            proc.kill()
            proc.wait()
        if not line.startswith("relay identity "):
            pytest.skip("relay daemon running without libcrypto: no identity")
        return line.rsplit(" ", 1)[-1]

    first = start_and_read_identity()
    assert identity_file.exists() and len(identity_file.read_bytes()) == 32
    assert start_and_read_identity() == first


async def test_relay_reregister_different_id_no_stale_route(relay_process):
    """One control line re-registering under a NEW peer_id must drop the route to its
    old id: a later DIAL for the old id gets a clean refusal (regression: the stale
    g_control entry used to deref a dangling conn and crash the daemon)."""
    from hivemind_tpu.p2p.peer_id import PeerID
    from hivemind_tpu.p2p.relay import RelayChannel, _recv_frame, _send_frame, register_control
    from hivemind_tpu.utils.crypto import Ed25519PrivateKey

    port = relay_process
    key_a, key_b = Ed25519PrivateKey(), Ed25519PrivateKey()
    id_a = PeerID.from_private_key(key_a).to_bytes()
    id_b = PeerID.from_private_key(key_b).to_bytes()

    r1, w1 = await _raw_conn(port)
    assert await register_control(RelayChannel(r1, w1), id_a, key_a) == b"O"
    assert await register_control(RelayChannel(r1, w1), id_b, key_b) == b"O"  # same line, new id

    rd, wd = await _raw_conn(port)
    await _send_frame(wd, b"D" + os.urandom(16) + id_a)
    try:
        refusal = await _recv_frame(rd)
    except asyncio.IncompleteReadError:
        refusal = b"E"  # abrupt close is also a refusal, not a crash
    assert refusal == b"E"
    wd.close()

    # the daemon is still alive and routes to the NEW id
    rd2, wd2 = await _raw_conn(port)
    await _send_frame(wd2, b"D" + os.urandom(16) + id_b)
    incoming = await _recv_frame(r1)
    assert incoming[:1] == b"I"
    for w in (w1, wd2):
        w.close()


async def test_relay_backpressure_bounds_memory(relay_process):
    """Fast sender + slow receiver: the daemon must PAUSE reading (epoll interest
    drop) instead of buffering at line rate; memory stays bounded and every byte
    still arrives once the receiver drains (ADVICE r1: level-triggered EPOLLIN)."""
    from hivemind_tpu.p2p.peer_id import PeerID
    from hivemind_tpu.p2p.relay import RelayChannel, _recv_frame, _send_frame, register_control
    from hivemind_tpu.utils.crypto import Ed25519PrivateKey

    port = relay_process
    total = 32 * 1024 * 1024
    server_key = Ed25519PrivateKey()
    peer_id = PeerID.from_private_key(server_key).to_bytes()

    rs, ws = await _raw_conn(port)
    assert await register_control(RelayChannel(rs, ws), peer_id, server_key) == b"O"

    rd, wd = await _raw_conn(port)
    token = os.urandom(16)
    await _send_frame(wd, b"D" + token + peer_id)
    incoming = await _recv_frame(rs)
    assert incoming[:1] == b"I"
    ra, wa = await _raw_conn(port)
    await _send_frame(wa, b"A" + incoming[1:17])
    assert await _recv_frame(ra) == b"O"
    assert await _recv_frame(rd) == b"O"

    # daemon RSS before the blast
    daemon_pid = None
    for line in subprocess.run(["pgrep", "-f", "relay_daemon"], capture_output=True, text=True).stdout.split():
        daemon_pid = int(line)

    def daemon_rss_kib() -> int:
        with open(f"/proc/{daemon_pid}/status") as f:
            for status_line in f:
                if status_line.startswith("VmRSS"):
                    return int(status_line.split()[1])
        return 0

    async def blast():
        chunk = b"x" * (1 << 20)
        for _ in range(total // len(chunk)):
            wd.write(chunk)
            await wd.drain()
        wd.write_eof()

    sender = asyncio.create_task(blast())
    await asyncio.sleep(2.0)  # receiver idle: pressure builds up
    mid_rss = daemon_rss_kib()
    # with working backpressure the daemon holds at most ~HIGH_WATER (512 KiB) +
    # one read of slack for this pair; 12 MiB of headroom still catches a broken
    # pause (the daemon would hold ~30 MiB within 2s on loopback).
    assert mid_rss < 12 * 1024, f"daemon ballooned to {mid_rss} KiB while receiver stalled"

    received = 0
    while True:
        data = await ra.read(1 << 16)
        if not data:
            break
        received += len(data)
    await sender
    assert received == total
    for w in (ws, wd, wa):
        w.close()


def test_plaintext_control_refused_by_default():
    """Encrypted-by-default posture (VERDICT r3 #7): a daemon that does not complete
    the encrypted handshake is REFUSED unless the caller explicitly opts out with
    allow_plaintext=True; a pinned identity refuses even under the opt-out."""
    from hivemind_tpu.p2p.relay import open_relay_channel

    async def scenario():
        async def legacy_daemon(reader, writer):
            # a pre-crypto daemon: closes on the unknown handshake frame
            await reader.read(64)
            writer.close()

        server = await asyncio.start_server(legacy_daemon, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        with pytest.raises(ConnectionError, match="refused by default"):
            await open_relay_channel("127.0.0.1", port)
        # explicit opt-out for a trusted legacy daemon still works...
        channel = await open_relay_channel("127.0.0.1", port, allow_plaintext=True)
        assert not channel.encrypted
        channel.close()
        # ...but a pinned identity always refuses, opt-out or not
        with pytest.raises(ConnectionError, match="pinned identity"):
            await open_relay_channel(
                "127.0.0.1", port, relay_pubkey=b"\x11" * 32, allow_plaintext=True
            )
        server.close()
        await server.wait_closed()

    asyncio.run(scenario())


async def test_data_plane_proxy_dial(relay_process):
    """Native data-plane proxy (VERDICT r3 #6): a client dials through the local
    daemon's 'X' mode — the daemon terminates the channel AEAD in C++ (Python
    ships plaintext frames over loopback), and unary + multi-megabyte streaming
    RPCs work bit-for-bit against an ordinary server that cannot tell the
    difference."""
    import numpy as np

    from hivemind_tpu.compression import serialize_tensor, split_tensor_for_streaming
    from hivemind_tpu.proto import runtime_pb2

    port = relay_process
    server = await P2P.create()
    client = await P2P.create(data_proxy_port=port)
    try:
        async def echo(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
            return test_pb2.TestResponse(number=request.number + 1)

        await server.add_protobuf_handler("echo", echo, test_pb2.TestRequest)
        await client.connect(server.get_visible_maddrs()[0])
        for i in (0, 7, 123456):
            response = await client.call_protobuf_handler(
                server.peer_id, "echo", test_pb2.TestRequest(number=i), test_pb2.TestResponse
            )
            assert response.number == i + 1

        received = []

        async def sink(requests, context: P2PContext):
            total = 0
            async for message in requests:
                for tensor in message.tensors:
                    total += len(tensor.buffer)
            received.append(total)
            yield runtime_pb2.ExpertResponse()

        await server.add_protobuf_handler(
            "sink", sink, runtime_pb2.ExpertRequest, stream_input=True, stream_output=True
        )
        payload = serialize_tensor(np.random.RandomState(0).randn(1_500_000).astype(np.float32))

        async def requests():
            for chunk in split_tensor_for_streaming(payload, 256 * 1024):
                yield runtime_pb2.ExpertRequest(uid="b", tensors=[chunk])

        async for _response in client.iterate_protobuf_handler(
            server.peer_id, "sink", requests(), runtime_pb2.ExpertResponse
        ):
            pass
        assert received and received[0] >= 6_000_000
    finally:
        await client.shutdown()
        await server.shutdown()


async def test_data_plane_proxy_over_unix_socket(relay_process_unix):
    """The proxy hop over the daemon's AF_UNIX listener: the socket file is 0600
    (kernel-enforced same-user trust boundary for the 'K' key handoff — the
    reference confines its daemon hop to a unix socket the same way,
    p2p_daemon.py:84-147), and dials through it carry RPCs end to end."""
    socket_path = relay_process_unix
    assert (os.stat(socket_path).st_mode & 0o777) == 0o600, oct(os.stat(socket_path).st_mode)

    server = await P2P.create()
    client = await P2P.create(data_proxy_path=socket_path)
    try:
        async def echo(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
            return test_pb2.TestResponse(number=request.number + 1)

        await server.add_protobuf_handler("echo", echo, test_pb2.TestRequest)
        await client.connect(server.get_visible_maddrs()[0])
        response = await client.call_protobuf_handler(
            server.peer_id, "echo", test_pb2.TestRequest(number=41), test_pb2.TestResponse
        )
        assert response.number == 42
        # the dial really rode the daemon (a refused proxy would silently fall
        # back to a direct dial and make this test vacuous)
        assert client._proxied_dials >= 1
    finally:
        await client.shutdown()
        await server.shutdown()


async def test_inbound_data_plane_proxy(relay_process):
    """VERDICT r4 next-round #7: the daemon owns the SERVER's public listener
    ('Y' mode) and terminates the inbound direction's AEAD too — a plain client
    dials the advertised (daemon-owned) port and RPCs work end to end, while the
    server's Python loop only ever sees plaintext frames on loopback. Combined
    with a proxied client dial, BOTH directions' cipher work is native."""
    port = relay_process
    server = await P2P.create(data_proxy_port=port, inbound_data_proxy=True)
    client = await P2P.create(data_proxy_port=port)  # outbound proxied too
    try:
        assert server._inbound_proxy_active, "inbound proxy registration failed"

        async def echo(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
            return test_pb2.TestResponse(number=request.number * 2)

        await server.add_protobuf_handler("echo", echo, test_pb2.TestRequest)
        maddr = server.get_visible_maddrs()[0]
        # the advertised port is the daemon's public listener, not the loopback bind
        assert maddr.port != server._listen_port
        await client.connect(maddr)
        for i in (3, 999):
            response = await client.call_protobuf_handler(
                server.peer_id, "echo", test_pb2.TestRequest(number=i), test_pb2.TestResponse
            )
            assert response.number == i * 2
        assert client._proxied_dials >= 1
    finally:
        await client.shutdown()
        await server.shutdown()


async def test_native_transport_zero_config():
    """`P2P.create(native_transport=True)` reproduces the reference's default
    posture with one flag: a PRIVATE daemon spawns on a 0600 unix socket, the
    public listener moves into it ('Y'), outbound dials ride 'X', and shutdown
    reaps the child — no ports, paths, or daemon management for the caller."""
    server = await P2P.create(native_transport=True)
    if server._native_daemon is None:
        await server.shutdown()
        pytest.skip("native toolchain unavailable: the designed asyncio fallback engaged")
    client = await P2P.create(native_transport=True)
    try:
        assert server._native_daemon is not None and server._native_daemon.alive
        assert server._inbound_proxy_active
        assert (os.stat(server._native_daemon.unix_path).st_mode & 0o777) == 0o600

        async def echo(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
            return test_pb2.TestResponse(number=request.number + 100)

        await server.add_protobuf_handler("echo", echo, test_pb2.TestRequest)
        await client.connect(server.get_visible_maddrs()[0])
        response = await client.call_protobuf_handler(
            server.peer_id, "echo", test_pb2.TestRequest(number=1), test_pb2.TestResponse
        )
        assert response.number == 101
        assert client._proxied_dials >= 1  # the dial rode the client's own daemon
    finally:
        server_proc = server._native_daemon.process if server._native_daemon else None
        await client.shutdown()
        await server.shutdown()
        if server_proc is not None:
            assert server_proc.poll() is not None, "daemon child leaked past shutdown"


async def test_inbound_proxy_daemon_death_falls_back_to_direct_listening():
    """If the daemon dies AFTER 'Y' registration, its public listener vanishes —
    the peer must notice (EOF watchdog on the control conn), fall back to a
    direct listener, and re-announce, instead of advertising a dead port forever
    while outbound dials keep working and mask the loss."""
    import time

    if not RELAY_BIN.exists():
        subprocess.run(["make"], cwd=NATIVE_DIR, check=True, capture_output=True)
    proc = subprocess.Popen([str(RELAY_BIN), "0"], stdout=subprocess.PIPE, text=True)
    port = int(proc.stdout.readline().strip().rsplit(" ", 1)[-1])
    proc.stdout.readline()
    server = await P2P.create(data_proxy_port=port, inbound_data_proxy=True)
    client = None
    try:
        assert server._inbound_proxy_active
        dead_public_port = server.get_visible_maddrs()[0].port
        proc.kill()
        proc.wait()
        deadline = time.monotonic() + 20
        while server._inbound_proxy_active and time.monotonic() < deadline:
            await asyncio.sleep(0.2)
        assert not server._inbound_proxy_active, "daemon death never detected"
        maddr = server.get_visible_maddrs()[0]
        assert maddr.port != dead_public_port  # re-announced the direct port

        async def echo(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
            return test_pb2.TestResponse(number=request.number - 1)

        await server.add_protobuf_handler("echo", echo, test_pb2.TestRequest)
        client = await P2P.create()
        await client.connect(maddr)
        response = await client.call_protobuf_handler(
            server.peer_id, "echo", test_pb2.TestRequest(number=43), test_pb2.TestResponse
        )
        assert response.number == 42
    finally:
        if client is not None:
            await client.shutdown()
        await server.shutdown()
        if proc.poll() is None:
            proc.kill()
            proc.wait()


async def test_inbound_proxy_survives_malformed_wire_frames(relay_process):
    """Adversarial bytes at the daemon-owned PUBLIC listener (the inbound fuzz
    half of the r4 ask): oversized frames, garbage ciphertext after a fake
    hello, and raw junk each kill at most their own pair — a well-formed peer
    still handshakes and RPCs afterwards."""
    import struct

    port = relay_process
    server = await P2P.create(data_proxy_port=port, inbound_data_proxy=True)
    client = None
    try:
        assert server._inbound_proxy_active
        public_port = server.get_visible_maddrs()[0].port

        # 1) oversized frame header: the daemon must tear the pair down (the
        # server's own hello may arrive first — both handshake sides send first
        # — so drain to EOF rather than expecting an instant close)
        reader, writer = await asyncio.open_connection("127.0.0.1", public_port)
        writer.write(struct.pack(">I", (64 << 20)) + b"x" * 64)
        await writer.drain()
        await asyncio.wait_for(reader.read(-1), timeout=10)  # returns only at EOF
        writer.close()

        # 2) plausible hello frame, then garbage "ciphertext" frames
        reader, writer = await asyncio.open_connection("127.0.0.1", public_port)
        writer.write(struct.pack(">I", 32) + b"h" * 32)
        for _ in range(4):
            writer.write(struct.pack(">I", 64) + b"\x00" * 64)
        await writer.drain()
        await asyncio.sleep(0.5)
        writer.close()

        # 3) raw junk, no framing at all
        reader, writer = await asyncio.open_connection("127.0.0.1", public_port)
        writer.write(b"\xff" * 1024)
        await writer.drain()
        writer.close()

        # the daemon and server survived: a real peer works
        client = await P2P.create()
        async def echo(request: test_pb2.TestRequest, context: P2PContext) -> test_pb2.TestResponse:
            return test_pb2.TestResponse(number=request.number + 7)

        await server.add_protobuf_handler("echo", echo, test_pb2.TestRequest)
        await client.connect(server.get_visible_maddrs()[0])
        response = await client.call_protobuf_handler(
            server.peer_id, "echo", test_pb2.TestRequest(number=1), test_pb2.TestResponse
        )
        assert response.number == 8
    finally:
        if client is not None:
            await client.shutdown()
        await server.shutdown()


async def test_data_plane_proxy_survives_malformed_frames(relay_process):
    """Adversarial input to the daemon's proxy parser must kill at most the
    offending pair, never the daemon: bad 'K' frames, oversized frames, and
    garbage ciphertext each get their connection closed, and a well-formed
    proxied dial still works afterwards."""
    import asyncio
    import struct

    port = relay_process

    async def frame(writer, payload: bytes):
        writer.write(struct.pack(">I", len(payload)) + payload)
        await writer.drain()

    async def open_proxy_to(target_port: int):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        await frame(writer, b"X" + struct.pack(">H", target_port) + b"127.0.0.1")
        header = await asyncio.wait_for(reader.readexactly(4), timeout=5)
        (length,) = struct.unpack(">I", header)
        assert await reader.readexactly(length) == b"O"
        return reader, writer

    # a sink the proxy can connect to
    sink_conns = []

    async def on_connect(reader, writer):
        sink_conns.append((reader, writer))

    sink = await asyncio.start_server(on_connect, "127.0.0.1", 0)
    sink_port = sink.sockets[0].getsockname()[1]

    # 1) frame #2 is not a valid 'K': pair must close (EOF), daemon survives
    reader, writer = await open_proxy_to(sink_port)
    await frame(writer, b"hello-crosses-raw")
    await frame(writer, b"K" + b"\x00" * 10)  # wrong length
    assert await reader.read(64) == b""  # daemon closed the pair
    writer.close()

    # 2) oversized frame header: pair closes, daemon survives
    reader, writer = await open_proxy_to(sink_port)
    writer.write(struct.pack(">I", (64 << 20)))  # 64 MiB > MAX_PROXY_FRAME
    await writer.drain()
    assert await reader.read(64) == b""
    writer.close()

    # 3) valid 'K' then garbage "plaintext" is fine to SEAL (any bytes seal), but
    #    garbage CIPHERTEXT from the remote side must fatal the pair: emulate by
    #    having the sink (the "remote") send a framed garbage blob after its hello
    reader, writer = await open_proxy_to(sink_port)
    await frame(writer, b"hello")
    await frame(writer, b"K" + b"\x01" * 32 + b"\x02" * 32 + b"\x00" * 16)
    await asyncio.sleep(0.1)
    sink_reader, sink_writer = sink_conns[-1]
    await sink_reader.readexactly(4 + 5)  # the forwarded raw hello
    sink_writer.write(struct.pack(">I", 5) + b"salut")  # remote hello: raw forward
    sink_writer.write(struct.pack(">I", 32) + b"\xff" * 32)  # not valid AEAD
    await sink_writer.drain()
    header = await asyncio.wait_for(reader.readexactly(4), timeout=5)
    (length,) = struct.unpack(">I", header)
    assert await reader.readexactly(length) == b"salut"
    assert await reader.read(64) == b""  # tampered wire frame killed the pair
    writer.close()

    # the daemon is still healthy: a fresh proxied pair round-trips bytes raw
    reader, writer = await open_proxy_to(sink_port)
    await frame(writer, b"ping")  # hello crosses raw
    await asyncio.sleep(0.1)
    sink_reader, sink_writer = sink_conns[-1]
    assert await asyncio.wait_for(sink_reader.readexactly(4 + 4), timeout=5) == struct.pack(">I", 4) + b"ping"
    writer.close()
    for _sink_reader, sink_writer in sink_conns:
        sink_writer.close()  # 3.12: Server.wait_closed waits for every live handler
    sink.close()
    await sink.wait_closed()
