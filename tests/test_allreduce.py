"""Partitioning, reduction, load balancing, and AllReduceRunner with hand-built
groups over real localhost transport (scope: reference tests/test_allreduce.py)."""

import asyncio
from typing import Dict, List

import numpy as np
import pytest

from hivemind_tpu.averaging.allreduce import AllReduceRunner, AveragingMode
from hivemind_tpu.averaging.load_balancing import hagenbach_bischoff, load_balance_peers
from hivemind_tpu.averaging.partition import TensorPartContainer, TensorPartReducer
from hivemind_tpu.compression import Float16Compression
from hivemind_tpu.p2p import P2P, P2PContext
from hivemind_tpu.proto import averaging_pb2


def make_tensors(seed=0):
    rng = np.random.RandomState(seed)
    return [
        rng.randn(1000).astype(np.float32),
        rng.randn(32, 16).astype(np.float32),
        rng.randn(7).astype(np.float32),
    ]


async def test_part_container_roundtrip():
    tensors = make_tensors()
    total = sum(t.size for t in tensors)
    counts = [total // 2, total - total // 2]
    container = TensorPartContainer(tensors, counts, part_size_bytes=800)

    # feeding back zero deltas reproduces... zero deltas per tensor
    for peer_index in range(2):
        parts = container.get_raw_input_parts(peer_index)
        assert sum(p.size for p in parts) == counts[peer_index]
        for part_index, part in enumerate(parts):
            container.register_processed_part(peer_index, part_index, part * 0.5)  # delta = half

    deltas = [d async for d in container.iterate_output_tensors()]
    flat_input = np.concatenate([t.reshape(-1) for t in tensors])
    flat_delta = np.concatenate([d.reshape(-1) for d in deltas])
    assert np.allclose(flat_delta, flat_input * 0.5, atol=1e-6)
    for tensor, delta in zip(tensors, deltas):
        assert delta.shape == tensor.shape


async def test_part_container_compressed_stream():
    tensors = make_tensors(1)
    total = sum(t.size for t in tensors)
    container = TensorPartContainer(tensors, [total], compression=Float16Compression(), part_size_bytes=1000)
    from hivemind_tpu.compression import deserialize_tensor

    restored = []
    async for serialized in container.iterate_input_parts_for(0):
        restored.append(deserialize_tensor(serialized))
    flat = np.concatenate([r.reshape(-1) for r in restored])
    original = np.concatenate([t.reshape(-1) for t in tensors])
    assert np.allclose(flat, original, atol=1e-2)


async def test_part_container_failed_reducer():
    tensors = make_tensors(2)
    total = sum(t.size for t in tensors)
    container = TensorPartContainer(tensors, [total // 3, total - total // 3], part_size_bytes=512)
    container.register_failed_reducer(0)
    for part_index, part in enumerate(container.get_raw_input_parts(1)):
        container.register_processed_part(1, part_index, np.ones_like(part))
    deltas = [d async for d in container.iterate_output_tensors()]
    flat_delta = np.concatenate([d.reshape(-1) for d in deltas])
    assert np.all(flat_delta[: total // 3] == 0)  # failed span keeps local values
    assert np.all(flat_delta[total // 3 :] == 1)
    assert container.failed_size == total // 3


async def test_reducer_weighted_average():
    reducer = TensorPartReducer([(10,), (5,)], num_senders=3)
    parts = [np.full(10, float(i)) for i in range(3)]

    results = await asyncio.gather(
        *(reducer.accumulate_part(i, 0, parts[i], weight=i + 1) for i in range(3))
    )
    expected = (parts[0] * 1 + parts[1] * 2 + parts[2] * 3) / 6
    for result in results:
        assert np.allclose(result, expected)


async def test_reducer_sender_failure_shrinks_denominator():
    reducer = TensorPartReducer([(4,)], num_senders=3)
    task0 = asyncio.create_task(reducer.accumulate_part(0, 0, np.full(4, 1.0), weight=1))
    task1 = asyncio.create_task(reducer.accumulate_part(1, 0, np.full(4, 3.0), weight=1))
    await asyncio.sleep(0.05)
    assert not task0.done()  # waiting for sender 2
    reducer.on_sender_failed(2)
    result = await asyncio.wait_for(task0, timeout=2)
    assert np.allclose(result, 2.0)  # average of survivors only
    assert np.allclose(await task1, 2.0)


def test_load_balancing():
    counts = load_balance_peers(1000, [1.0, 1.0, 1.0, 1.0])
    assert sum(counts) == 1000 and max(counts) - min(counts) <= 1

    counts = load_balance_peers(1000, [10.0, 1.0])
    assert sum(counts) == 1000 and counts[0] > counts[1]

    counts = load_balance_peers(1000, [1.0, None, 1.0, 0])  # two clients
    assert sum(counts) == 1000 and counts[1] == 0 and counts[3] == 0

    counts = load_balance_peers(1000, [7.0, None])
    assert counts == (1000, 0)

    with pytest.raises(ValueError):
        load_balance_peers(100, [None, None])

    assert list(hagenbach_bischoff(10, np.array([0.5, 0.3, 0.2]))) == [5, 3, 2]


class _AllreduceHarness:
    """Minimal averager stand-in: registers rpc_aggregate_part per peer and routes
    streams to that peer's runner."""

    def __init__(self, p2p: P2P):
        self.p2p = p2p
        self.runner = None

    async def register(self):
        async def rpc_aggregate_part(requests, context: P2PContext):
            first = await requests.__anext__()
            assert self.runner is not None
            async for message in self.runner.handle_aggregate_stream(first, requests, context):
                yield message

        await self.p2p.add_protobuf_handler(
            "DecentralizedAverager.rpc_aggregate_part",
            rpc_aggregate_part,
            averaging_pb2.AveragingData,
            stream_input=True,
            stream_output=True,
        )

    def get_stub(self, peer_id):
        harness_p2p = self.p2p

        class _Stub:
            def rpc_aggregate_part(self, requests, timeout=None):
                return harness_p2p.iterate_protobuf_handler(
                    peer_id, "DecentralizedAverager.rpc_aggregate_part", requests, averaging_pb2.AveragingData
                )

        return _Stub()


async def run_allreduce_group(n_peers: int, modes: List[AveragingMode], counts_override=None, weights=None):
    """Build a real group over localhost TCP and run one full all-reduce."""
    p2ps = [await P2P.create() for _ in range(n_peers)]
    for i, p2p in enumerate(p2ps):
        for other in p2ps[:i]:
            await p2p.connect(other.get_visible_maddrs()[0])
    harnesses = [_AllreduceHarness(p) for p in p2ps]
    for harness in harnesses:
        await harness.register()

    peer_tensors = {i: make_tensors(seed=i) for i in range(n_peers)}
    total = sum(t.size for t in peer_tensors[0])
    if counts_override is None:
        reducers = [i for i, m in enumerate(modes) if m != AveragingMode.CLIENT]
        base = total // len(reducers)
        counts = [0] * n_peers
        for j, i in enumerate(reducers):
            counts[i] = base + (total - base * len(reducers) if j == 0 else 0)
    else:
        counts = counts_override
    weights = weights or [1.0 if m != AveragingMode.AUX else 0.0 for m in modes]
    ordered_peer_ids = [p.peer_id for p in p2ps]

    group_id = b"test-group-0123"
    runners = []
    for i in range(n_peers):
        runner = AllReduceRunner(
            p2p=p2ps[i],
            group_id=group_id,
            tensors=peer_tensors[i] if modes[i] != AveragingMode.AUX else peer_tensors[0],
            ordered_peer_ids=ordered_peer_ids,
            peer_element_counts=counts,
            modes=modes,
            get_stub=harnesses[i].get_stub,
            weight=weights[i],
            sender_timeout=5.0,
            reducer_timeout=10.0,
        )
        harnesses[i].runner = runner
        runners.append(runner)

    async def run_one(i):
        deltas = [d async for d in runners[i].run()]
        return deltas

    all_deltas = await asyncio.gather(*(run_one(i) for i in range(n_peers)))
    for p2p in p2ps:
        await p2p.shutdown()
    return peer_tensors, all_deltas, weights


async def test_allreduce_two_nodes():
    modes = [AveragingMode.NODE, AveragingMode.NODE]
    peer_tensors, all_deltas, weights = await run_allreduce_group(2, modes)
    expected = [
        np.mean([peer_tensors[i][k] for i in range(2)], axis=0) for k in range(3)
    ]
    for i in range(2):
        for k in range(3):
            averaged = peer_tensors[i][k] + all_deltas[i][k].reshape(peer_tensors[i][k].shape)
            assert np.allclose(averaged, expected[k], atol=1e-5), f"peer {i} tensor {k}"


async def test_allreduce_four_nodes_weighted():
    modes = [AveragingMode.NODE] * 4
    weights = [1.0, 2.0, 3.0, 4.0]
    peer_tensors, all_deltas, _ = await run_allreduce_group(4, modes, weights=weights)
    total_w = sum(weights)
    expected = [
        sum(peer_tensors[i][k] * weights[i] for i in range(4)) / total_w for k in range(3)
    ]
    for i in range(4):
        for k in range(3):
            averaged = peer_tensors[i][k] + all_deltas[i][k].reshape(peer_tensors[i][k].shape)
            assert np.allclose(averaged, expected[k], atol=1e-4), f"peer {i} tensor {k}"


async def test_allreduce_client_and_aux_modes():
    # peer0: NODE, peer1: CLIENT (sends only, reduces nothing), peer2: AUX (reduces only)
    modes = [AveragingMode.NODE, AveragingMode.CLIENT, AveragingMode.AUX]
    total = sum(t.size for t in make_tensors())
    counts = [total // 2, 0, total - total // 2]
    peer_tensors, all_deltas, _ = await run_allreduce_group(3, modes, counts_override=counts)
    # only NODE and CLIENT contribute data (AUX weight 0); both should get the average
    expected = [
        np.mean([peer_tensors[0][k], peer_tensors[1][k]], axis=0) for k in range(3)
    ]
    for i in (0, 1):
        for k in range(3):
            averaged = peer_tensors[i][k] + all_deltas[i][k].reshape(peer_tensors[i][k].shape)
            assert np.allclose(averaged, expected[k], atol=1e-5), f"peer {i} tensor {k}"
    assert all_deltas[2] == []  # aux yields nothing
