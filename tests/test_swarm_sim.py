"""The in-process swarm simulator (ISSUE 12, ROADMAP item 5).

Tier-1 scope: virtual-clock mechanics, the LinkMatrix/partition model, the
SimP2P transport seam under the real DHT, a ~100-peer composite smoke (DHT
store/get fan-out under churn + link-scoped chaos, matchmaking convergence
across a two-region partition, beam search over a small grid — all under
seeded latency) and the same-seed-twice determinism contract. The 1k-peer
soak rides the chaos suite as a slow test.
"""

import asyncio
import json

import pytest

from hivemind_tpu.resilience import CHAOS
from hivemind_tpu.sim import (
    LinkMatrix,
    LinkProfile,
    Partition,
    SimNetwork,
    SimPeer,
    VirtualClockEventLoop,
    install_virtual_time,
    run_scenario,
    uninstall_virtual_time,
)
from hivemind_tpu.utils.timed_storage import get_dht_time


@pytest.fixture(autouse=True)
def _restore_wall_time():
    yield
    uninstall_virtual_time()


# ---------------------------------------------------------------------- clock


def test_virtual_clock_jumps_instead_of_waiting():
    loop = VirtualClockEventLoop(start_time=5000.0)
    try:
        asyncio.set_event_loop(loop)

        async def main():
            t0 = loop.time()
            await asyncio.sleep(120.0)  # two virtual minutes, ~zero wall time
            return loop.time() - t0

        import time

        wall0 = time.perf_counter()
        elapsed = loop.run_until_complete(main())
        wall = time.perf_counter() - wall0
        assert elapsed >= 120.0
        assert wall < 5.0  # the sleep must not happen in wall time
    finally:
        asyncio.set_event_loop(None)
        loop.close()


def test_virtual_clock_orders_timers_and_survives_sub_ulp_timeouts():
    # start at epoch magnitude where a double's ulp (~1.2e-7) exceeds tiny
    # timer gaps — the regression that froze the first implementation
    loop = VirtualClockEventLoop(start_time=1_000_000_000.0)
    try:
        asyncio.set_event_loop(loop)
        order = []

        async def sleeper(delay, tag):
            await asyncio.sleep(delay)
            order.append(tag)

        async def main():
            await asyncio.gather(
                sleeper(0.003, "c"), sleeper(1e-9, "a"), sleeper(0.002, "b")
            )

        loop.run_until_complete(main())
        assert order == ["a", "b", "c"]
    finally:
        asyncio.set_event_loop(None)
        loop.close()


def test_virtual_clock_drives_dht_time():
    loop = VirtualClockEventLoop(start_time=777.0)
    install_virtual_time(loop)
    try:
        assert get_dht_time() == 777.0
    finally:
        uninstall_virtual_time()
        loop.close()
    assert get_dht_time() > 1_000_000_000  # wall time restored


# ---------------------------------------------------------------------- link matrix


def test_link_matrix_seeded_and_region_aware():
    links = LinkMatrix(
        seed=9,
        intra=LinkProfile(delay=0.002, bandwidth=125e6, jitter=0.1),
        inter=LinkProfile(delay=0.08, bandwidth=12.5e6, jitter=0.25),
    )
    intra = links.spec("a", "b", "east", "east")
    inter = links.spec("a", "c", "east", "west")
    assert intra.delay < inter.delay
    assert intra.bandwidth > inter.bandwidth
    # per-link jitter is fixed and directional links may differ, but the same
    # (seed, link) always resolves identically
    assert links.spec("a", "c", "east", "west") == inter
    assert LinkMatrix(seed=9, intra=links.intra, inter=links.inter).spec(
        "a", "c", "east", "west"
    ) == inter
    # a different seed moves the jitter
    assert LinkMatrix(seed=10, intra=links.intra, inter=links.inter).spec(
        "a", "c", "east", "west"
    ) != inter


def test_partition_schedule_severs_both_directions():
    links = LinkMatrix(seed=1, partitions=(Partition.between("east", "west", 10.0, 20.0),))
    assert not links.partitioned("east", "west", 5.0)
    assert links.partitioned("east", "west", 10.0)
    assert links.partitioned("west", "east", 15.0)
    assert not links.partitioned("east", "east", 15.0)
    assert not links.partitioned("east", "west", 20.0)


# ---------------------------------------------------------------------- transport seam


def test_sim_transport_runs_real_dht_store_get_with_latency():
    """Two real DHTNodes over SimP2P: bootstrap, store, cross-peer get — and the
    whole exchange costs virtual link time, not wall time."""
    loop = VirtualClockEventLoop()
    install_virtual_time(loop)
    try:
        asyncio.set_event_loop(loop)

        async def main():
            net = SimNetwork(LinkMatrix(seed=3), seed=3)
            a = await SimPeer.create(net, "a", "east")
            b = await SimPeer.create(net, "b", "west", bootstrap=a.bootstrap_maddrs())
            t0 = loop.time()
            assert await a.node.store("k", "v", get_dht_time() + 60)
            found = await b.node.get("k")
            assert found is not None and found.value == "v"
            assert loop.time() > t0  # messages paid link delay in virtual time
            assert net.counters["messages"] > 0 and net.counters["bytes"] > 0
            await a.shutdown()
            await b.shutdown()
            await net.shutdown()

        loop.run_until_complete(main())
    finally:
        uninstall_virtual_time()
        asyncio.set_event_loop(None)
        loop.close()


def test_sim_partition_blocks_in_flight_and_new_traffic():
    loop = VirtualClockEventLoop()
    install_virtual_time(loop)
    try:
        asyncio.set_event_loop(loop)

        async def main():
            links = LinkMatrix(seed=4)
            net = SimNetwork(links, seed=4)
            a = await SimPeer.create(net, "a", "east")
            b = await SimPeer.create(net, "b", "west", bootstrap=a.bootstrap_maddrs())
            assert await a.node.store("k", "v", get_dht_time() + 600)
            # sever now
            links.partitions = (Partition.between("east", "west", 0.0, 1e9),)
            ok = await a.node.protocol.call_ping(b.peer_id)
            assert ok is None  # RPC failed cleanly, caller saw unreachable
            assert net.counters["dropped_partition"] > 0
            await a.shutdown()
            await b.shutdown()
            await net.shutdown()

        loop.run_until_complete(main())
    finally:
        uninstall_virtual_time()
        asyncio.set_event_loop(None)
        loop.close()


def test_sim_chaos_link_scope_composes_with_transport():
    """A drop rule scoped to one direction of one link makes that peer's RPCs
    fail while the reverse direction keeps working (satellite: the chaos
    catalog composes with the sim's per-link scoping)."""
    loop = VirtualClockEventLoop()
    install_virtual_time(loop)
    try:
        asyncio.set_event_loop(loop)

        async def main():
            net = SimNetwork(LinkMatrix(seed=6), seed=6)
            a = await SimPeer.create(net, "a")
            b = await SimPeer.create(net, "b", bootstrap=a.bootstrap_maddrs())
            CHAOS.clear()
            CHAOS.reseed(6)
            rule = CHAOS.add_rule(
                "p2p.unary.send", "drop", scope=f"link:{a.peer_id}->{b.peer_id}"
            )
            assert await a.node.protocol.call_ping(b.peer_id) is None  # a->b dropped
            assert await b.node.protocol.call_ping(a.peer_id) is not None  # b->a clean
            assert rule.hits >= 1
            CHAOS.clear()
            await a.shutdown()
            await b.shutdown()
            await net.shutdown()

        loop.run_until_complete(main())
    finally:
        CHAOS.clear()
        uninstall_virtual_time()
        asyncio.set_event_loop(None)
        loop.close()


# ---------------------------------------------------------------------- store batching satellite


def test_store_many_grouped_traversal_places_records_findably():
    """dht/node.py store_many batches keys with coinciding local neighborhoods
    into shared traversals; a bulk publish (>= grouping threshold) must still
    leave every key retrievable from another peer."""
    loop = VirtualClockEventLoop()
    install_virtual_time(loop)
    try:
        asyncio.set_event_loop(loop)

        async def main():
            net = SimNetwork(LinkMatrix(seed=8), seed=8)
            a = await SimPeer.create(net, "a")
            b = await SimPeer.create(net, "b", bootstrap=a.bootstrap_maddrs())
            c = await SimPeer.create(net, "c", bootstrap=a.bootstrap_maddrs())
            keys = [f"bulk-{i:03d}" for i in range(40)]  # above _STORE_GROUPING_MIN_KEYS
            result = await a.node.store_many(keys, [f"v{i}" for i in range(40)], get_dht_time() + 600)
            assert all(result.values())
            found = await c.node.get_many(keys)
            values = {k: (found[k].value if found[k] is not None else None) for k in keys}
            assert values == {f"bulk-{i:03d}": f"v{i}" for i in range(40)}
            for peer in (a, b, c):
                await peer.shutdown()
            await net.shutdown()

        loop.run_until_complete(main())
    finally:
        uninstall_virtual_time()
        asyncio.set_event_loop(None)
        loop.close()


# ---------------------------------------------------------------------- scenarios


def test_smoke_scenario_composite():
    """The ~100-peer tier-1 smoke: DHT fan-out under churn with a link-scoped
    chaos rule, beam search vs oracle on a small grid, matchmaking convergence
    across a two-region partition — all under seeded latency."""
    result = run_scenario("smoke", seed=11)
    s = result.summary
    assert s["chaos_link_rule_hits"] > 0
    assert s["dht"]["publish_messages"] > 0
    assert s["dht"]["get_success_rate"] >= 0.9
    assert s["beam"]["recall_at_beam"] >= 0.95
    mm = s["matchmaking"]
    assert mm["groups_during"] > 0, "matchmaking must keep converging inside partition islands"
    assert mm["cross_region_during_settled"] == 0, "no groups may span a severed link"
    assert mm["convergence_during"] >= 0.75
    assert mm["cross_region_post"] > 0, "regions must mix again after heal"
    # chaos rule was removed by the scenario; nothing may leak into other tests
    assert not CHAOS.enabled


def test_same_seed_twice_is_bit_identical():
    params = dict(peers=24, regions=2, keys=40, churn_fraction=0.15, probe_samples=20,
                  matchmaking_peers=6, matchmaking_rounds=1)
    first = run_scenario("dht_churn", seed=21, **params)
    second = run_scenario("dht_churn", seed=21, **params)
    assert first.canonical() == second.canonical()
    assert first.digest() == second.digest()
    # a different seed must actually change the run (the digest is not vacuous)
    third = run_scenario("dht_churn", seed=22, **params)
    assert third.digest() != first.digest()
    # and the summary is real JSON with the scale facts the bench records
    parsed = json.loads(first.canonical())
    assert parsed["peers"] == 24 and parsed["probes"] == 20


# ---------------------------------------------------------------------- slow soak (chaos suite)


@pytest.mark.slow
@pytest.mark.chaos
def test_thousand_peer_soak_deterministic():
    """ROADMAP acceptance: a 1000-peer DHT + matchmaking scenario completes on
    CPU in under 5 minutes of wall time and produces bit-identical summaries
    across two runs with the same seed."""
    params = dict(peers=1000, regions=4, keys=1000, churn_fraction=0.10,
                  probe_samples=200, matchmaking_peers=32, matchmaking_rounds=1)
    first = run_scenario("dht_churn", seed=42, **params)
    assert first.diagnostics["wall_seconds"] < 300, first.diagnostics
    assert first.summary["get_success_rate"] >= 0.9
    assert first.summary["matchmaking"]["groups_formed"] > 0
    second = run_scenario("dht_churn", seed=42, **params)
    assert first.digest() == second.digest()


@pytest.mark.slow
@pytest.mark.chaos
def test_ten_thousand_expert_beam_recall():
    """ROADMAP acceptance: recall@beam >= 0.95 vs the brute-force oracle at 10k
    experts with no partitions active."""
    result = run_scenario("beam_routing", seed=42, peers=100, servers=50,
                          grid=(10, 10, 100), beam_size=8, trials=8)
    assert result.summary["experts"] == 10_000
    assert result.summary["recall_at_beam"] >= 0.95, result.summary
