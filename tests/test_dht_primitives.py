"""Unit tests for DHT building blocks: DHTID, routing table, local storage, traversal
(scope: reference tests/test_routing.py + test_dht_storage.py)."""

import asyncio
import heapq
import random

import pytest

from hivemind_tpu.dht.routing import DHTID, KBucket, PeerInfo, RoutingTable
from hivemind_tpu.dht.storage import DHTLocalStorage, DictionaryDHTValue
from hivemind_tpu.dht.traverse import simple_traverse_dht, traverse_dht
from hivemind_tpu.p2p.peer_id import PeerID
from hivemind_tpu.utils.serializer import MSGPackSerializer
from hivemind_tpu.utils.timed_storage import get_dht_time


def fake_peer_info(seed: int) -> PeerInfo:
    return PeerInfo(PeerID(seed.to_bytes(8, "big")), (f"/ip4/127.0.0.1/tcp/{seed % 60000 + 1024}",))


def test_dhtid_basics():
    key_id = DHTID.generate(source=b"key")
    assert key_id == DHTID.generate(source=b"key")  # deterministic for keys
    assert key_id != DHTID.generate(source=b"key2")
    assert DHTID.from_bytes(key_id.to_bytes()) == key_id
    a, b = DHTID.generate(), DHTID.generate()
    assert a.xor_distance(a) == 0
    assert a.xor_distance(b) == b.xor_distance(a)
    c = DHTID.generate()
    # triangle inequality of xor metric
    assert a.xor_distance(c) <= a.xor_distance(b) ^ b.xor_distance(c) or True  # xor: d(a,c) = d(a,b)^d(b,c)
    assert a.xor_distance(c) == a.xor_distance(b) ^ b.xor_distance(c)
    # msgpack-able sources
    assert DHTID.generate(source=("tuple", 1)) == DHTID.generate(source=("tuple", 1))


def test_kbucket_eviction_and_replacements():
    bucket = KBucket(0, 2**256, size=3)
    ids = [DHTID.generate() for _ in range(5)]
    for i, node_id in enumerate(ids[:3]):
        assert bucket.add_or_update_node(node_id, fake_peer_info(i))
    assert not bucket.add_or_update_node(ids[3], fake_peer_info(3))  # full
    assert ids[3] in bucket.replacement_nodes
    # removing a live node promotes the replacement
    bucket.remove_node(ids[0])
    assert ids[0] not in bucket.nodes_to_peers and ids[3] in bucket.nodes_to_peers


def test_routing_table_split_and_nearest():
    own_id = DHTID.generate()
    table = RoutingTable(own_id, bucket_size=8)
    random.seed(42)
    all_ids = [DHTID.generate() for _ in range(200)]
    for i, node_id in enumerate(all_ids):
        table.add_or_update_node(node_id, fake_peer_info(i))
    assert len(table.buckets) > 1  # must have split
    assert all(b.lower < b.upper for b in table.buckets)
    # buckets tile the id space contiguously
    for left, right in zip(table.buckets, table.buckets[1:]):
        assert left.upper == right.lower
    assert table.buckets[0].lower == 0 and table.buckets[-1].upper == 2**256

    query = DHTID.generate()
    nearest = table.get_nearest_neighbors(query, k=10)
    in_table = list(table.uid_to_info.keys())
    expected = heapq.nsmallest(10, in_table, key=query.xor_distance)
    assert [nid for nid, _ in nearest] == expected


def test_local_storage_dictionary_semantics():
    storage = DHTLocalStorage()
    key = DHTID.generate(source=b"k")
    now = get_dht_time()
    assert storage.store_subkey(key, "alpha", b"1", now + 10)
    assert storage.store_subkey(key, "beta", b"2", now + 20)
    entry = storage.get(key)
    assert isinstance(entry.value, DictionaryDHTValue)
    assert entry.value.get("alpha").value == b"1"
    assert entry.expiration_time == now + 20  # container tracks the latest subkey
    # stale subkey write rejected
    assert not storage.store_subkey(key, "alpha", b"0", now + 5)
    # plain value older than the dictionary's latest subkey must not clobber it
    assert not storage.store(key, b"plain", now + 15)
    assert isinstance(storage.get(key).value, DictionaryDHTValue)
    # but a fresher plain value wins
    assert storage.store(key, b"plain", now + 30)
    assert storage.get(key).value == b"plain"


def test_dictionary_value_serialization():
    d = DictionaryDHTValue()
    now = get_dht_time()
    d.store("x", b"1", now + 10)
    d.store(("tuple", "subkey"), b"2", now + 20)
    restored = MSGPackSerializer.loads(MSGPackSerializer.dumps(d))
    assert isinstance(restored, DictionaryDHTValue)
    assert restored == d
    assert restored.latest_expiration_time == d.latest_expiration_time


def make_fake_swarm(num_nodes: int, k: int, seed: int = 0):
    """A static fake swarm where every node has a real Kademlia routing table over all
    other nodes — get_neighbors answers like rpc_find does (k nearest to the QUERY from
    the peer's table). A plain kNN graph would not be navigable under the xor metric;
    bucketed tables cover every distance scale, which is what makes the search converge."""
    random.seed(seed)
    node_ids = [DHTID.generate() for _ in range(num_nodes)]
    tables = {}
    for node in node_ids:
        table = RoutingTable(node, bucket_size=k)
        for i, other in enumerate(node_ids):
            if other != node:
                table.add_or_update_node(other, fake_peer_info(i))
        tables[node] = table

    async def get_neighbors(peer, queries):
        await asyncio.sleep(random.random() * 0.001)
        return {
            q: ([nid for nid, _ in tables[peer].get_nearest_neighbors(q, k)], False) for q in queries
        }

    return node_ids, get_neighbors


async def test_traverse_matches_exhaustive_search():
    # bucket_size >= swarm size: full knowledge, so beam search must be *exact*
    # (the reference's beam-vs-exhaustive test makes the same assumption)
    node_ids, get_neighbors = make_fake_swarm(60, k=60)
    beam_size = 10
    query = DHTID.generate()
    initial = random.sample(node_ids, 3)

    simple_nearest, _ = await simple_traverse_dht(query, initial, beam_size, get_neighbors)
    nearest, visited = await traverse_dht(
        [query], initial, beam_size, num_workers=3, queries_per_call=1, get_neighbors=get_neighbors
    )
    exhaustive = heapq.nsmallest(beam_size, node_ids, key=query.xor_distance)
    assert simple_nearest == exhaustive
    assert nearest[query] == exhaustive


async def test_traverse_navigability_with_small_buckets():
    # with small buckets, far-region precision is approximate, but the query's own
    # neighborhood is finely bucketed: the closest nodes must always be found
    node_ids, get_neighbors = make_fake_swarm(100, k=8, seed=3)
    for _ in range(3):
        query = DHTID.generate()
        initial = random.sample(node_ids, 3)
        nearest, _ = await traverse_dht(
            [query], initial, beam_size=10, num_workers=3, queries_per_call=1,
            get_neighbors=get_neighbors,
        )
        exhaustive = heapq.nsmallest(10, node_ids, key=query.xor_distance)
        assert nearest[query][:3] == exhaustive[:3]


async def test_traverse_multiple_queries_and_callbacks():
    node_ids, get_neighbors = make_fake_swarm(50, k=50, seed=1)
    queries = [DHTID.generate() for _ in range(4)]
    initial = random.sample(node_ids, 3)
    finished = []

    async def callback(query, nearest, visited):
        finished.append(query)

    nearest, visited = await traverse_dht(
        queries, initial, beam_size=8, num_workers=4, queries_per_call=3,
        get_neighbors=get_neighbors, found_callback=callback,
    )
    assert sorted(finished) == sorted(queries)
    for query in queries:
        exhaustive = heapq.nsmallest(8, node_ids, key=query.xor_distance)
        assert nearest[query] == exhaustive


async def test_traverse_early_stop():
    node_ids, base_get_neighbors = make_fake_swarm(50, k=5, seed=2)
    query = DHTID.generate()
    stop_at = heapq.nsmallest(3, node_ids, key=query.xor_distance)[-1]
    calls = []

    async def get_neighbors(peer, queries):
        calls.append(peer)
        out = await base_get_neighbors(peer, queries)
        if peer == stop_at:
            return {q: (n, True) for q, (n, _) in out.items()}
        return out

    nearest, _ = await traverse_dht(
        [query], random.sample(node_ids, 3), beam_size=10, num_workers=1, queries_per_call=1,
        get_neighbors=get_neighbors,
    )
    # should_stop truncates the search once the target peer responds
    assert stop_at in calls
