"""SliceOptimizer scheduling guards (ISSUE 2 satellites): the broadcast-skip
window is capped by locally-known samples remaining to target_batch_size, and a
delayed round whose thread outlives its join timeout poisons the grad averager
(loud log + telemetry counter) instead of silently racing its buffers."""

import threading

import jax
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hivemind_tpu.dht import DHT
from hivemind_tpu.optim import SliceOptimizer
from hivemind_tpu.optim.progress_tracker import GlobalTrainingProgress
from hivemind_tpu.telemetry import REGISTRY
from hivemind_tpu.utils.timed_storage import get_dht_time


@pytest.fixture
def slice_opt():
    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    opt = SliceOptimizer(
        mesh=mesh,
        params={"w": jax.device_put(np.zeros((8, 4), np.float32), NamedSharding(mesh, P("dp")))},
        optimizer=optax.sgd(0.1),
        dht_factory=lambda: DHT(start=True),
        run_id="guards_test",
        target_batch_size=4096,
        batch_size_per_step=16,
        max_broadcast_skip=8,
    )
    try:
        yield opt
    finally:
        opt.shutdown()


def _set_global_progress(opt, samples_accumulated: int, eta_s: float = 1000.0) -> None:
    opt.tracker.global_progress = GlobalTrainingProgress(
        global_epoch=0,
        samples_accumulated=samples_accumulated,
        target_batch_size=opt.target_batch_size,
        num_peers=2,
        num_clients=0,
        eta_next_epoch=get_dht_time() + eta_s,
        next_fetch_time=get_dht_time() + eta_s,
    )


def test_suggest_skip_capped_by_remaining_samples(slice_opt):
    slice_opt._step_time_ema = 0.01  # far from the boundary in step-time terms

    # plenty of samples remaining: the ETA term dominates, full skip granted
    _set_global_progress(slice_opt, samples_accumulated=0)
    assert slice_opt._suggest_skip(False, False, False) == 8

    # 32 samples remaining at 16/step with the 2x margin -> at most 1 skip,
    # even though the (stale) ETA still claims the boundary is ~1000s away
    _set_global_progress(slice_opt, samples_accumulated=4064)
    assert slice_opt._suggest_skip(False, False, False) == 1

    # target already reached locally: no broadcast-free steps at all
    _set_global_progress(slice_opt, samples_accumulated=4096)
    assert slice_opt._suggest_skip(False, False, False) == 0

    # anything needing low-latency signaling still disables the skip entirely
    _set_global_progress(slice_opt, samples_accumulated=0)
    assert slice_opt._suggest_skip(True, False, False) == 0
    assert slice_opt._suggest_skip(False, True, False) == 0
    assert slice_opt._suggest_skip(False, False, True) == 0


def _poison_counter() -> float:
    metric = REGISTRY.get("hivemind_optim_poisoned_averager_rounds_total")
    return metric.value() if metric is not None else 0.0


def test_timed_out_discard_poisons_grad_averager(slice_opt):
    release = threading.Event()
    wedged = threading.Thread(target=release.wait, daemon=True)
    wedged.start()
    slice_opt._pending = {"scratch": [], "num_peers": 2}
    slice_opt._bg_thread = wedged
    slice_opt.averaging_timeout = -30.0  # join timeout (averaging_timeout + 30) == 0

    before = _poison_counter()
    slice_opt._discard_pending()
    assert slice_opt._bg_thread is None and slice_opt._pending is None
    assert slice_opt._grad_averager_poisoned()
    assert _poison_counter() == before + 1

    # while poisoned: rounds refuse the shared buffers (degrade to local)...
    assert slice_opt._run_swarm_round([np.zeros(4, np.float32)], 1.0, None) is False
    # ...and pre-scheduling declines to claim a control
    slice_opt._maybe_schedule_gradient_averaging()
    assert slice_opt.scheduled_grads is None

    # once the thread is confirmed dead the poison clears itself
    release.set()
    wedged.join(timeout=5.0)
    assert not slice_opt._grad_averager_poisoned()


def test_clean_discard_does_not_poison(slice_opt):
    done = threading.Thread(target=lambda: None)
    done.start()
    done.join()
    slice_opt._pending = {"scratch": [], "num_peers": 2}
    slice_opt._bg_thread = done
    before = _poison_counter()
    slice_opt._discard_pending()
    assert not slice_opt._grad_averager_poisoned()
    assert _poison_counter() == before
