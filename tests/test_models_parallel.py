"""Flagship model + parallel layer: ALBERT forward/loss, ring attention vs plain
attention equivalence, multi-device sharded training step on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from hivemind_tpu.models import AlbertConfig, AlbertForMaskedLM, make_synthetic_mlm_batch, make_train_step, mlm_loss
from hivemind_tpu.parallel import make_mesh, params_shardings, plain_attention, ring_attention


def test_albert_forward_and_shapes():
    config = AlbertConfig.tiny()
    model = AlbertForMaskedLM(config)
    batch = make_synthetic_mlm_batch(jax.random.PRNGKey(0), config, batch_size=2, seq_len=16)
    params = model.init(jax.random.PRNGKey(1), batch["input_ids"])["params"]
    logits = model.apply({"params": params}, batch["input_ids"])
    assert logits.shape == (2, 16, config.vocab_size)
    assert logits.dtype == jnp.float32
    loss = mlm_loss(logits, batch["labels"], batch["mlm_mask"])
    assert np.isfinite(float(loss)) and float(loss) > 0
    # parameter sharing: one layer's worth of encoder params regardless of depth
    deep = AlbertForMaskedLM(AlbertConfig.tiny(num_layers=6))
    deep_params = deep.init(jax.random.PRNGKey(1), batch["input_ids"])["params"]
    count = lambda p: sum(x.size for x in jax.tree_util.tree_leaves(p))
    assert count(deep_params) == count(params)


def test_albert_training_reduces_loss():
    config = AlbertConfig.tiny()
    optimizer = optax.adam(1e-3)
    model, train_step = make_train_step(config, optimizer)
    batch = make_synthetic_mlm_batch(jax.random.PRNGKey(0), config, batch_size=4, seq_len=32)
    params = model.init(jax.random.PRNGKey(1), batch["input_ids"])["params"]
    opt_state = optimizer.init(params)
    step = jax.jit(train_step)
    first_loss = None
    for _ in range(30):
        loss, params, opt_state = step(params, opt_state, batch)
        first_loss = first_loss if first_loss is not None else float(loss)
    assert float(loss) < first_loss * 0.7, f"loss {first_loss} -> {float(loss)}"


def test_ring_attention_matches_plain():
    """Ring attention over the sp axis must reproduce single-device attention."""
    mesh = make_mesh(dp=1, tp=1, sp=4)
    batch, seq, heads, dim = 2, 32, 4, 8
    rng = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(key, (batch, seq, heads, dim), jnp.float32)
        for key in jax.random.split(rng, 3)
    )
    expected = plain_attention(q, k, v)

    from functools import partial
    from hivemind_tpu.parallel._compat import shard_map

    spec = P(None, "sp", None, None)
    ring = shard_map(
        partial(ring_attention, axis_name="sp"),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    with mesh:
        result = jax.jit(ring)(q, k, v)
    assert np.allclose(np.asarray(result), np.asarray(expected), atol=1e-4)


def test_causal_ring_attention_matches_plain():
    """CAUSAL ring attention over contiguous sequence shards == single-device causal
    attention: past shards contribute fully, the local shard causally, future shards
    not at all."""
    from functools import partial
    from hivemind_tpu.parallel._compat import shard_map

    mesh = make_mesh(dp=1, tp=1, sp=4)
    batch, seq, heads, dim = 2, 32, 4, 8
    rng = jax.random.PRNGKey(2)
    q, k, v = (
        jax.random.normal(key, (batch, seq, heads, dim), jnp.float32)
        for key in jax.random.split(rng, 3)
    )
    expected = plain_attention(q, k, v, causal=True)

    spec = P(None, "sp", None, None)
    ring = shard_map(
        partial(ring_attention, axis_name="sp", causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    with mesh:
        result = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(result), np.asarray(expected), rtol=1e-4, atol=1e-5)


def test_causal_lm_trains_and_shards():
    """The decoder-only flagship: loss decreases on one chip, and the same step
    compiles and descends under a dp×tp×sp mesh with causal ring attention."""
    from hivemind_tpu.models import CausalLMConfig, make_causal_train_step, make_synthetic_lm_batch

    config = CausalLMConfig.tiny()
    optimizer = optax.adam(1e-3)
    model, train_step = make_causal_train_step(config, optimizer)
    batch = make_synthetic_lm_batch(jax.random.PRNGKey(0), config, 4, 32)
    params = model.init(jax.random.PRNGKey(1), batch["input_ids"])["params"]
    opt_state = optimizer.init(params)
    step = jax.jit(train_step)
    first_loss = None
    for _ in range(25):
        loss, params, opt_state = step(params, opt_state, batch)
        first_loss = first_loss if first_loss is not None else float(loss)
    assert float(loss) < first_loss * 0.8, (first_loss, float(loss))

    mesh = make_mesh(dp=2, tp=2, sp=2)
    sharded_config = CausalLMConfig.tiny(mesh=mesh)
    model, train_step = make_causal_train_step(sharded_config, optimizer)
    batch = make_synthetic_lm_batch(jax.random.PRNGKey(0), sharded_config, 4, 32)
    params = model.init(jax.random.PRNGKey(1), batch["input_ids"])["params"]
    opt_state = optimizer.init(params)
    params = jax.device_put(params, params_shardings(params, mesh))
    batch = jax.device_put(batch, NamedSharding(mesh, P("dp", "sp")))
    with mesh:
        step = jax.jit(train_step)
        loss1, params, opt_state = step(params, opt_state, batch)
        loss2, _, _ = step(params, opt_state, batch)
    assert np.isfinite(float(loss1)) and float(loss2) < float(loss1)
    q_kernel = params["layer_0"]["query"]["kernel"]
    assert "tp" in str(q_kernel.sharding.spec)


def test_ring_flash_attention_matches_plain():
    """Flash-core ring attention (per-step Pallas kernel + log-sum-exp shard merge,
    interpret mode on CPU) must reproduce single-device attention, and its
    recompute-backward must match plain attention's gradients."""
    from functools import partial

    from hivemind_tpu.parallel._compat import NO_CHECK as no_check, shard_map
    from hivemind_tpu.parallel.ring_attention import ring_flash_attention

    mesh = make_mesh(dp=1, tp=1, sp=4)
    batch, seq, heads, dim = 2, 512, 2, 16  # 128 per shard: one full flash block
    rng = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(key, (batch, seq, heads, dim), jnp.float32)
        for key in jax.random.split(rng, 3)
    )
    expected = plain_attention(q, k, v)

    spec = P(None, "sp", None, None)
    ring = shard_map(
        partial(ring_flash_attention, axis_name="sp", interpret=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        **no_check,  # the vma/rep checker can't see through pallas_call outputs
    )
    with mesh:
        result = jax.jit(ring)(q, k, v)
    assert np.allclose(np.asarray(result), np.asarray(expected), atol=1e-4)

    # bf16 inputs (the flagship model's compute dtype) must trace and stay close:
    # the scan carries are fp32 regardless of input dtype
    q16, k16, v16 = (x.astype(jnp.bfloat16) for x in (q, k, v))
    with mesh:
        result16 = jax.jit(ring)(q16, k16, v16)
    assert result16.dtype == jnp.bfloat16
    assert np.allclose(
        np.asarray(result16, np.float32), np.asarray(expected), atol=0.05
    )

    # CAUSAL flash ring: local block via the kernel's causal path, future shards
    # excluded by lse = -inf before the merge
    causal_ring = shard_map(
        partial(ring_flash_attention, axis_name="sp", interpret=True, causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        **no_check,
    )
    with mesh:
        causal_result = jax.jit(causal_ring)(q, k, v)
    assert np.allclose(
        np.asarray(causal_result), np.asarray(plain_attention(q, k, v, causal=True)), atol=1e-4
    )

    # gradients flow through the custom_vjp einsum-ring recompute
    def ring_loss(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def plain_loss(q, k, v):
        return jnp.sum(plain_attention(q, k, v) ** 2)

    with mesh:
        ring_grads = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    plain_grads = jax.grad(plain_loss, argnums=(0, 1, 2))(q, k, v)
    for rg, pg in zip(ring_grads, plain_grads):
        np.testing.assert_allclose(np.asarray(rg), np.asarray(pg), rtol=1e-3, atol=1e-4)


def test_sharded_training_step_8_devices():
    """Full dp×tp×sp sharded train step on the virtual 8-device mesh — the same path
    the driver's dryrun_multichip exercises."""
    mesh = make_mesh(dp=2, tp=2, sp=2)
    config = AlbertConfig.tiny(mesh=mesh)
    optimizer = optax.sgd(1e-2)
    model, train_step = make_train_step(config, optimizer)
    batch = make_synthetic_mlm_batch(jax.random.PRNGKey(0), config, batch_size=4, seq_len=32)
    params = model.init(jax.random.PRNGKey(1), batch["input_ids"])["params"]
    opt_state = optimizer.init(params)

    shardings = params_shardings(params, mesh)
    params = jax.device_put(params, shardings)
    batch_sharded = jax.device_put(
        batch, NamedSharding(mesh, P("dp", "sp"))
    )
    with mesh:
        step = jax.jit(train_step)
        loss, new_params, new_opt_state = step(params, opt_state, batch_sharded)
        loss2, _, _ = step(new_params, new_opt_state, batch_sharded)
    assert np.isfinite(float(loss)) and np.isfinite(float(loss2))
    assert float(loss2) < float(loss)  # sgd on the same batch must descend
    # tp sharding actually applied to attention kernels
    q_kernel = new_params["shared_layer"]["query"]["kernel"]
    assert "tp" in str(q_kernel.sharding.spec)


def test_masked_only_loss_equals_full_loss():
    """loss_masked_only with a sufficient budget equals the full-logits mlm_loss
    (the bench's throughput lever must not change the objective)."""
    from hivemind_tpu.models import AlbertConfig, AlbertForMaskedLM, make_synthetic_mlm_batch, mlm_loss

    config = AlbertConfig.tiny(max_position=64)
    model = AlbertForMaskedLM(config)
    batch = make_synthetic_mlm_batch(jax.random.PRNGKey(0), config, 4, 64)
    params = model.init(jax.random.PRNGKey(1), batch["input_ids"][:1, :8])["params"]

    full = mlm_loss(
        model.apply({"params": params}, batch["input_ids"]), batch["labels"], batch["mlm_mask"]
    )
    masked = model.apply(
        {"params": params}, batch["input_ids"], batch["labels"], batch["mlm_mask"], 32,
        method=AlbertForMaskedLM.loss_masked_only,
    )
    np.testing.assert_allclose(float(masked), float(full), rtol=1e-5)

    # gradients agree too (the actual training signal), across EVERY parameter
    import optax
    from hivemind_tpu.models import make_train_step

    updated = {}
    for fraction in (0.5, None):
        _model, step = make_train_step(config, optax.sgd(0.1), masked_loss_fraction=fraction)
        opt_state = optax.sgd(0.1).init(params)
        loss, new_params, _ = jax.jit(step)(params, opt_state, batch)
        updated[fraction] = new_params
    for masked_leaf, full_leaf in zip(
        jax.tree_util.tree_leaves(updated[0.5]), jax.tree_util.tree_leaves(updated[None])
    ):
        # bf16 compute: gathering positions before the head reorders reductions,
        # so per-element grads differ by bf16 noise (~1% rel), not exactly
        np.testing.assert_allclose(
            np.asarray(masked_leaf), np.asarray(full_leaf), rtol=0.05, atol=1e-4
        )


def test_remat_training_step_matches_plain():
    """remat=True must be numerically identical (same params, same math, only the
    backward-pass activation strategy changes) — it is purely a memory/batch lever."""
    import optax

    from hivemind_tpu.models import AlbertConfig, make_synthetic_mlm_batch, make_train_step

    results = {}
    for remat in (False, True):
        config = AlbertConfig.tiny(max_position=64, remat=remat)
        model, step = make_train_step(config, optax.sgd(0.1))
        batch = make_synthetic_mlm_batch(jax.random.PRNGKey(0), config, 4, 64)
        params = model.init(jax.random.PRNGKey(1), batch["input_ids"][:1, :8])["params"]
        opt_state = optax.sgd(0.1).init(params)
        loss, new_params, _ = jax.jit(step)(params, opt_state, batch)
        results[remat] = (float(loss), new_params)

    assert results[False][0] == results[True][0], "remat changed the loss"
    for plain_leaf, remat_leaf in zip(
        jax.tree_util.tree_leaves(results[False][1]), jax.tree_util.tree_leaves(results[True][1])
    ):
        # the recompute changes XLA fusion boundaries, so bf16 rounding in the
        # backward pass differs slightly; the training signal must still agree
        np.testing.assert_allclose(
            np.asarray(plain_leaf), np.asarray(remat_leaf), rtol=0.05, atol=1e-3
        )


def test_pallas_flash_attention_matches_plain():
    """Fused flash kernel (interpret mode on CPU) == reference einsum attention,
    bidirectional + causal, including a seq that is not a block multiple, and
    gradients flow through the custom_vjp recompute path."""
    import numpy as np
    from hivemind_tpu.ops.pallas_attention import flash_attention
    from hivemind_tpu.parallel.ring_attention import plain_attention

    rng = np.random.RandomState(0)
    for seq in (128, 192, 320):  # 192/320: padded tail blocks + multi-block carry
        q, k, v = (
            jnp.asarray(rng.randn(2, seq, 4, 16).astype(np.float32)) for _ in range(3)
        )
        for causal in (False, True):
            fused = flash_attention(q, k, v, causal, True)
            exact = plain_attention(q, k, v, causal=causal)
            np.testing.assert_allclose(np.asarray(fused), np.asarray(exact), rtol=2e-5, atol=2e-5)

    q, k, v = (jnp.asarray(rng.randn(1, 128, 2, 8).astype(np.float32)) for _ in range(3))
    loss_fused = lambda q: flash_attention(q, k, v, True, True).sum()
    loss_exact = lambda q: plain_attention(q, k, v, causal=True).sum()
    np.testing.assert_allclose(
        np.asarray(jax.grad(loss_fused)(q)), np.asarray(jax.grad(loss_exact)(q)),
        rtol=2e-5, atol=2e-5,
    )


def test_pallas_flash_backward_kernels_match_plain_grads():
    """The FUSED two-pass backward (dQ / dK+dV kernels from the saved lse) must
    reproduce the einsum path's gradients for all inputs — bidirectional and
    causal, block-aligned and padded, with a non-uniform cotangent so dP/delta
    terms are actually exercised (VERDICT r2 item 7)."""
    import numpy as np
    from hivemind_tpu.ops.pallas_attention import flash_attention
    from hivemind_tpu.parallel.ring_attention import plain_attention

    rng = np.random.RandomState(1)
    w = jnp.asarray(np.cos(np.arange(16)), jnp.float32)  # non-uniform cotangent
    for causal in (False, True):
        for seq in (128, 200):
            q, k, v = (
                jnp.asarray(rng.randn(2, seq, 4, 16).astype(np.float32)) for _ in range(3)
            )
            loss_fused = lambda q, k, v: (flash_attention(q, k, v, causal, True) * w).sum()
            loss_exact = lambda q, k, v: (plain_attention(q, k, v, causal=causal) * w).sum()
            grads_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
            grads_exact = jax.grad(loss_exact, argnums=(0, 1, 2))(q, k, v)
            for name, gf, ge in zip("qkv", grads_fused, grads_exact):
                np.testing.assert_allclose(
                    np.asarray(gf), np.asarray(ge), rtol=2e-4, atol=2e-5,
                    err_msg=f"d{name} causal={causal} seq={seq}",
                )
