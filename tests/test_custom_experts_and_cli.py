"""Custom expert registration end-to-end + CLI smoke tests
(scope: reference tests/test_custom_experts.py, test_cli_scripts.py, test_start_server.py)."""

import subprocess
import sys
import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest


def test_register_custom_expert_end_to_end():
    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe import RemoteExpert, Server, get_experts, register_expert_class

    class GatedExpert(nn.Module):
        hidden_dim: int

        @nn.compact
        def __call__(self, x):
            gate = nn.sigmoid(nn.Dense(self.hidden_dim)(x))
            return x * gate

    register_expert_class("gated_test", lambda batch, hid: np.zeros((batch, hid), np.float32))(GatedExpert)

    server = Server.create(
        expert_uids=["gated_test_grid.0"], expert_cls="gated_test", hidden_dim=16,
        start=True, optim_factory=lambda: optax.sgd(1e-3),
    )
    try:
        time.sleep(1.0)
        info = get_experts(server.dht, ["gated_test_grid.0"])[0]
        assert info is not None
        client_dht = DHT(initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True)
        expert = RemoteExpert(info, client_dht.node.p2p)
        x = jnp.asarray(np.random.RandomState(0).randn(3, 16), jnp.float32)
        out = expert(x)
        backend = server.backends["gated_test_grid.0"]
        expected = backend.module.apply({"params": backend.params}, x)
        assert np.allclose(np.asarray(out), np.asarray(expected), atol=1e-4)
        client_dht.shutdown()
    finally:
        server.shutdown()
        server.dht.shutdown()


@pytest.mark.parametrize(
    "module,extra",
    [
        ("hivemind_tpu.hivemind_cli.run_dht", ["--refresh_period", "1"]),
        (
            "hivemind_tpu.hivemind_cli.run_server",
            ["--expert_uids", "cli_test.0", "--hidden_dim", "16", "--expert_cls", "ffn"],
        ),
    ],
    ids=["run_dht", "run_server"],
)
def test_cli_starts_and_listens(module, extra):
    """The real CLI entrypoints come up and announce a dialable address."""
    import os

    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": "."}
    proc = subprocess.Popen(
        [sys.executable, "-m", module, *extra],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )
    try:
        import selectors

        # select-based read loop: a silent-but-alive child must FAIL at the deadline,
        # not block the whole suite inside readline()
        sel = selectors.DefaultSelector()
        sel.register(proc.stdout, selectors.EVENT_READ)
        deadline = time.monotonic() + 60
        saw_listening = False
        buffer = ""
        while time.monotonic() < deadline and not saw_listening:
            if not sel.select(timeout=1.0):
                if proc.poll() is not None:
                    break
                continue
            chunk = proc.stdout.readline()
            if not chunk:
                break
            buffer += chunk
            if "listening" in chunk:
                saw_listening = True
        assert saw_listening, f"{module} never announced a listening address; output: {buffer[-500:]}"
    finally:
        proc.kill()
        proc.wait()
