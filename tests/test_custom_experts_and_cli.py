"""Custom expert registration end-to-end + CLI smoke tests
(scope: reference tests/test_custom_experts.py, test_cli_scripts.py, test_start_server.py)."""

import subprocess
import sys
import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest


def read_child_until(proc, marker: str, timeout: float = 60.0) -> str:
    """Accumulate a child's stdout until ``marker`` appears, EOF, or the deadline.

    Reads the RAW non-blocking fd in chunks: selecting on the fd and then calling
    ``readline()`` silently strands any second line inside the TextIO buffer (the
    fd shows no data, the selector never fires again) — a hang this helper exists
    to avoid. The child must be started with stdout=PIPE, stderr=STDOUT."""
    import os
    import selectors

    import codecs

    fd = proc.stdout.fileno()
    os.set_blocking(fd, False)
    decoder = codecs.getincrementaldecoder("utf-8")("replace")
    deadline = time.monotonic() + timeout
    seen = ""
    with selectors.DefaultSelector() as sel:
        sel.register(fd, selectors.EVENT_READ)
        while time.monotonic() < deadline and marker not in seen:
            if not sel.select(timeout=1.0):
                if proc.poll() is not None:
                    break
                continue
            chunk = os.read(fd, 65536)
            if not chunk:
                break  # EOF
            seen += decoder.decode(chunk)
    return seen


def test_register_custom_expert_end_to_end():
    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe import RemoteExpert, Server, get_experts, register_expert_class

    class GatedExpert(nn.Module):
        hidden_dim: int

        @nn.compact
        def __call__(self, x):
            gate = nn.sigmoid(nn.Dense(self.hidden_dim)(x))
            return x * gate

    register_expert_class("gated_test", lambda batch, hid: np.zeros((batch, hid), np.float32))(GatedExpert)

    server = Server.create(
        expert_uids=["gated_test_grid.0"], expert_cls="gated_test", hidden_dim=16,
        start=True, optim_factory=lambda: optax.sgd(1e-3),
    )
    try:
        time.sleep(1.0)
        info = get_experts(server.dht, ["gated_test_grid.0"])[0]
        assert info is not None
        client_dht = DHT(initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True)
        expert = RemoteExpert(info, client_dht.node.p2p)
        x = jnp.asarray(np.random.RandomState(0).randn(3, 16), jnp.float32)
        out = expert(x)
        backend = server.backends["gated_test_grid.0"]
        expected = backend.module.apply({"params": backend.params}, x)
        # fp16 wire tolerance: the server's default activation compression is
        # negotiated via the DHT record this test resolved (exact-wire behavior
        # is covered by test_serving_compression.py)
        assert np.allclose(np.asarray(out), np.asarray(expected), atol=2e-2)
        client_dht.shutdown()
    finally:
        server.shutdown()
        server.dht.shutdown()


@pytest.mark.parametrize(
    "module,extra",
    [
        ("hivemind_tpu.hivemind_cli.run_dht", ["--refresh_period", "1"]),
        (
            "hivemind_tpu.hivemind_cli.run_server",
            ["--expert_uids", "cli_test.0", "--hidden_dim", "16", "--expert_cls", "ffn"],
        ),
    ],
    ids=["run_dht", "run_server"],
)
def test_cli_starts_and_listens(module, extra):
    """The real CLI entrypoints come up and announce a dialable address."""
    import os

    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": "."}
    proc = subprocess.Popen(
        [sys.executable, "-m", module, *extra],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )
    try:
        buffer = read_child_until(proc, "listening", timeout=60)
        assert "listening" in buffer, (
            f"{module} never announced a listening address; output: {buffer[-500:]}"
        )
    finally:
        proc.kill()
        proc.wait()


def test_run_server_custom_module_path(tmp_path):
    """--custom_module_path imports a user file whose @register_expert_class
    decorators run before the server builds experts (reference custom_experts.py)."""
    custom = tmp_path / "my_experts.py"
    custom.write_text(
        "import flax.linen as nn\n"
        "import numpy as np\n"
        "from hivemind_tpu.moe import register_expert_class\n\n"
        "@register_expert_class('scaled_cli', lambda b, h: np.zeros((b, h), np.float32))\n"
        "class Scaled(nn.Module):\n"
        "    hidden_dim: int\n"
        "    @nn.compact\n"
        "    def __call__(self, x):\n"
        "        return x * self.param('s', nn.initializers.ones, ())\n"
    )
    import os

    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": "."}
    proc = subprocess.Popen(
        [sys.executable, "-m", "hivemind_tpu.hivemind_cli.run_server",
         "--expert_uids", "scaled_cli_grid.0", "--expert_cls", "scaled_cli",
         "--hidden_dim", "8", "--custom_module_path", str(custom), "--platform", "cpu"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )
    try:
        seen = read_child_until(proc, "serving 1 experts", timeout=60)
        assert "serving 1 experts" in seen, f"server did not start: {seen[-2000:]}"
        assert "loaded custom expert module" in seen
    finally:
        proc.kill()
        proc.wait()
