"""MoE server throughput (parity: reference benchmarks/benchmark_throughput.py —
baselines 28,581 samples/s fwd+bwd, 97,604 fwd-only on a GTX 1080 Ti)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import threading
import time

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_experts", type=int, default=4)
    parser.add_argument("--hidden_dim", type=int, default=1024)
    parser.add_argument("--num_clients", type=int, default=8)
    parser.add_argument("--batches_per_client", type=int, default=8)
    parser.add_argument("--batch_size", type=int, default=512)
    parser.add_argument("--backward", action="store_true", help="also run backward passes")
    parser.add_argument("--expert_cls", default="ffn",
                        help="registered expert class; input shape comes from its "
                             "registry schema (block classes take [batch, seq, hid])")
    parser.add_argument("--decode_clients", type=int, default=0,
                        help=">0: measure KV-session decoding — this many concurrent "
                             "1-token streams through one block (continuous batching)")
    parser.add_argument("--decode_steps", type=int, default=64,
                        help="tokens per decode client")
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()

    if args.platform is None:
        args.platform = "cpu"
    apply_platform(args)
    import jax

    jax.devices()

    import optax

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe import RemoteExpert, Server, get_experts

    uids = [f"bench_expert.{i}" for i in range(args.num_experts)]
    server = Server.create(
        expert_uids=uids, expert_cls=args.expert_cls, hidden_dim=args.hidden_dim,
        max_batch_size=8192, start=True, optim_factory=lambda: optax.sgd(1e-3),
    )
    from hivemind_tpu.moe.server.layers import name_to_input

    # the registry schema defines each class's input shape; swap in batch_size
    sample = name_to_input[args.expert_cls](args.batch_size, args.hidden_dim)
    assert not isinstance(sample, tuple), "multi-input expert classes are not benchmarked here"
    sample_shape = sample.shape
    time.sleep(1.0)
    client_dht = DHT(initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True)
    infos = get_experts(client_dht, uids)
    assert all(info is not None for info in infos), "experts not discoverable"
    experts = [RemoteExpert(info, client_dht.node.p2p) for info in infos]

    if args.decode_clients:
        # continuous-batching decode: N clients each own a KV session on ONE block
        # and step one token at a time; concurrent steps merge into vmapped device
        # calls server-side (A/B with HIVEMIND_TPU_DECODE_BATCHING=0)
        import uuid

        block = experts[0]
        prompt, hid = 8, args.hidden_dim
        sessions = [uuid.uuid4().hex for _ in range(args.decode_clients)]
        rng = np.random.RandomState(0)
        prompts = rng.randn(args.decode_clients, 1, prompt, hid).astype(np.float32)
        for session, chunk in zip(sessions, prompts):
            block.decode_np(chunk, session, reset=True)
        token = rng.randn(1, 1, hid).astype(np.float32)
        done = [0] * args.decode_clients
        errors = []

        # untimed warmup round: trigger the batched-step compiles (pow2 buckets)
        # so short measured runs aren't dominated by jit time
        warmup = [threading.Thread(target=block.decode_np, args=(token, s, False))
                  for s in sessions]
        for t in warmup:
            t.start()
        for t in warmup:
            t.join()

        def decode_loop(index: int):
            try:
                for _ in range(args.decode_steps):
                    block.decode_np(token, sessions[index], reset=False)
                    done[index] += 1
            except Exception as e:
                errors.append((index, repr(e)))

        start = time.perf_counter()
        threads = [threading.Thread(target=decode_loop, args=(i,))
                   for i in range(args.decode_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - start
        manager = server.handler.decode_sessions
        print(json.dumps({
            "metric": "moe_decode_tokens_per_sec_aggregate",
            "value": round(sum(done) / elapsed, 1),
            "unit": "tokens/s",
            "extra": {
                "decode_clients": args.decode_clients, "steps_per_client": args.decode_steps,
                "hidden_dim": args.hidden_dim, "expert_cls": args.expert_cls,
                "batching": manager.batching_enabled,
                "batched_signatures": sorted(s for _, s in manager._batched_fns),
                "errors": errors[:3],
            },
        }))
        client_dht.shutdown()
        server.shutdown()
        server.dht.shutdown()
        return

    processed = [0] * args.num_clients
    errors = []

    def client_loop(index: int):
        rng = np.random.RandomState(index)
        try:
            for b in range(args.batches_per_client):
                x = rng.randn(*sample_shape).astype(np.float32)
                expert = experts[(index + b) % len(experts)]
                out = expert.forward_np(x)[0]
                if args.backward:
                    expert.backward_np(x, np.ones_like(out))
                processed[index] += args.batch_size
        except Exception as e:
            errors.append((index, repr(e)))

    start = time.perf_counter()
    threads = [threading.Thread(target=client_loop, args=(i,)) for i in range(args.num_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - start

    total = sum(processed)
    print(json.dumps({
        "metric": "moe_server_samples_per_sec" + ("_fwd_bwd" if args.backward else "_fwd"),
        "value": round(total / elapsed, 1),
        "unit": "samples/s",
        "extra": {
            "experts": args.num_experts, "clients": args.num_clients,
            "hidden_dim": args.hidden_dim, "expert_cls": args.expert_cls,
            "errors": errors[:3],
        },
    }))
    client_dht.shutdown()
    server.shutdown()
    server.dht.shutdown()


if __name__ == "__main__":
    main()
