"""MoE server throughput (parity: reference benchmarks/benchmark_throughput.py —
baselines 28,581 samples/s fwd+bwd, 97,604 fwd-only on a GTX 1080 Ti)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import threading
import time

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_experts", type=int, default=4)
    parser.add_argument("--hidden_dim", type=int, default=1024)
    parser.add_argument("--num_clients", type=int, default=8)
    parser.add_argument("--batches_per_client", type=int, default=8)
    parser.add_argument("--batch_size", type=int, default=512)
    parser.add_argument("--backward", action="store_true", help="also run backward passes")
    parser.add_argument("--expert_cls", default="ffn",
                        help="registered expert class; input shape comes from its "
                             "registry schema (block classes take [batch, seq, hid])")
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()

    if args.platform is None:
        args.platform = "cpu"
    apply_platform(args)
    import jax

    jax.devices()

    import optax

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe import RemoteExpert, Server, get_experts

    uids = [f"bench_expert.{i}" for i in range(args.num_experts)]
    server = Server.create(
        expert_uids=uids, expert_cls=args.expert_cls, hidden_dim=args.hidden_dim,
        max_batch_size=8192, start=True, optim_factory=lambda: optax.sgd(1e-3),
    )
    from hivemind_tpu.moe.server.layers import name_to_input

    # the registry schema defines each class's input shape; swap in batch_size
    sample = name_to_input[args.expert_cls](args.batch_size, args.hidden_dim)
    assert not isinstance(sample, tuple), "multi-input expert classes are not benchmarked here"
    sample_shape = sample.shape
    time.sleep(1.0)
    client_dht = DHT(initial_peers=[str(m) for m in server.dht.get_visible_maddrs()], start=True)
    infos = get_experts(client_dht, uids)
    assert all(info is not None for info in infos), "experts not discoverable"
    experts = [RemoteExpert(info, client_dht.node.p2p) for info in infos]

    processed = [0] * args.num_clients
    errors = []

    def client_loop(index: int):
        rng = np.random.RandomState(index)
        try:
            for b in range(args.batches_per_client):
                x = rng.randn(*sample_shape).astype(np.float32)
                expert = experts[(index + b) % len(experts)]
                out = expert.forward_np(x)[0]
                if args.backward:
                    expert.backward_np(x, np.ones_like(out))
                processed[index] += args.batch_size
        except Exception as e:
            errors.append((index, repr(e)))

    start = time.perf_counter()
    threads = [threading.Thread(target=client_loop, args=(i,)) for i in range(args.num_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - start

    total = sum(processed)
    print(json.dumps({
        "metric": "moe_server_samples_per_sec" + ("_fwd_bwd" if args.backward else "_fwd"),
        "value": round(total / elapsed, 1),
        "unit": "samples/s",
        "extra": {
            "experts": args.num_experts, "clients": args.num_clients,
            "hidden_dim": args.hidden_dim, "expert_cls": args.expert_cls,
            "errors": errors[:3],
        },
    }))
    client_dht.shutdown()
    server.shutdown()
    server.dht.shutdown()


if __name__ == "__main__":
    main()
