"""Petals-style Llama block serving from a real checkpoint (BASELINE config #5):
synthesizes an HF-layout sharded safetensors checkpoint at the requested shape
(or uses --checkpoint), loads it into llama_block backends (optionally int8
weight-only), serves over RPC, and measures KV-cache decode tok/s through
RemoteSequential."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import tempfile
import threading
import time
from pathlib import Path

import numpy as np


def _is_shed(error: BaseException) -> bool:
    """True when the terminal error (or anything on its cause chain — decode
    failover wraps the typed shed in a RuntimeError) is a server load-shed."""
    from hivemind_tpu.telemetry.serving import is_overload_error

    seen = set()
    while error is not None and id(error) not in seen:
        seen.add(id(error))
        if is_overload_error(error):
            return True
        error = error.__cause__ or error.__context__
    return False


def synthesize_checkpoint(path: Path, hidden: int, heads: int, kv_heads: int,
                          inner: int, layers: int) -> None:
    from safetensors.numpy import save_file

    rng = np.random.RandomState(0)
    (path / "config.json").write_text(json.dumps({
        "hidden_size": hidden, "num_attention_heads": heads,
        "num_key_value_heads": kv_heads, "intermediate_size": inner,
        "num_hidden_layers": layers, "rope_theta": 10000.0,
    }))
    head_dim = hidden // heads
    weight_map = {}
    scale = 1.0 / np.sqrt(hidden)
    for layer in range(layers):
        prefix = f"model.layers.{layer}."
        tensors = {
            prefix + "self_attn.q_proj.weight": rng.randn(heads * head_dim, hidden) * scale,
            prefix + "self_attn.k_proj.weight": rng.randn(kv_heads * head_dim, hidden) * scale,
            prefix + "self_attn.v_proj.weight": rng.randn(kv_heads * head_dim, hidden) * scale,
            prefix + "self_attn.o_proj.weight": rng.randn(hidden, hidden) * scale,
            prefix + "mlp.gate_proj.weight": rng.randn(inner, hidden) * scale,
            prefix + "mlp.up_proj.weight": rng.randn(inner, hidden) * scale,
            prefix + "mlp.down_proj.weight": rng.randn(hidden, inner) * scale,
            prefix + "input_layernorm.weight": np.ones(hidden),
            prefix + "post_attention_layernorm.weight": np.ones(hidden),
        }
        shard = f"model-{layer:05d}-of-{layers:05d}.safetensors"
        save_file({k: v.astype(np.float32) for k, v in tensors.items()}, path / shard)
        weight_map.update({name: shard for name in tensors})
    (path / "model.safetensors.index.json").write_text(json.dumps({"weight_map": weight_map}))


def run_multi_client(args, checkpoint: Path) -> None:
    """Skewed multi-tenant load generator (ISSUE 13): one HOT client decoding
    flat-out + N paced background clients, each with its own DHT identity (the
    server attributes and rate-limits per client id). Optional second replica
    of every block (multi-value DHT records; clients balance/hedge/fail over)
    and a mid-run crash-kill of that replica. Emits per-client tok/s and p99
    step latency; ANY non-shed client-visible failure voids the run (exit 1),
    and with --client_rate armed a shed on a BACKGROUND client (the hot tenant
    eating someone else's budget) also voids it."""
    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe import RemoteSequential
    from hivemind_tpu.moe.server.llama_loader import load_llama_blocks
    from hivemind_tpu.moe.server.server import Server
    from hivemind_tpu.telemetry import REGISTRY

    backends, config = load_llama_blocks(checkpoint, uid_prefix="lb.")
    num_blocks = len(backends)
    dht_primary = DHT(start=True)
    maddrs = [str(m) for m in dht_primary.get_visible_maddrs()]
    server_primary = Server(
        dht_primary, backends, decode_max_len=args.decode_max_len,
        activation_compression=args.activation_compression,
        client_rate=args.client_rate, client_burst=args.client_burst,
    )
    server_primary.run_in_background(await_ready=True)
    dht_replica = server_replica = None
    if args.replicas == 2:
        backends_replica, _config = load_llama_blocks(checkpoint, uid_prefix="lb.")
        dht_replica = DHT(initial_peers=maddrs, start=True)
        server_replica = Server(
            dht_replica, backends_replica, decode_max_len=args.decode_max_len,
            activation_compression=args.activation_compression,
            client_rate=args.client_rate, client_burst=args.client_burst,
        )
        server_replica.run_in_background(await_ready=True)
    time.sleep(1.0)

    rng = np.random.RandomState(1)
    hidden = rng.randn(1, args.prompt + args.generate, config.hidden_size).astype(np.float32)
    specs = [{"name": "hot", "interval": 0.0}] + [
        {"name": f"bg{i}", "interval": args.background_interval}
        for i in range(args.multi_client)
    ]
    stop = threading.Event()
    report = {}
    killed = {"at": None}

    def run_client(spec):
        client_dht = DHT(initial_peers=maddrs, start=True)
        pipe = RemoteSequential(client_dht, "lb.", num_blocks)
        latencies, failures = [], []
        tokens = sheds = episodes = 0
        started = time.perf_counter()
        try:
            while not stop.is_set():
                episodes += 1
                session = f"{spec['name']}_{episodes}"
                try:
                    pipe.decode_step(hidden[:, : args.prompt], session, reset=True)
                except Exception as e:
                    if _is_shed(e):
                        sheds += 1
                        time.sleep(0.1)
                        continue
                    failures.append(repr(e))
                    break
                for t in range(args.generate):
                    if stop.is_set():
                        break
                    pos = args.prompt + t
                    step_start = time.perf_counter()
                    try:
                        pipe.decode_step(hidden[:, pos : pos + 1], session)
                    except Exception as e:
                        if _is_shed(e):
                            sheds += 1
                            time.sleep(0.1)
                            break  # bucket dry: restart a fresh episode when refilled
                        failures.append(repr(e))
                        break
                    latencies.append(time.perf_counter() - step_start)
                    tokens += 1
                    if spec["interval"]:
                        time.sleep(spec["interval"])
                else:
                    pipe.close_decode_session(session)
                    continue
                pipe.close_decode_session(session)
                if failures:
                    break
        finally:
            elapsed = max(time.perf_counter() - started, 1e-9)
            entry = {
                "tokens": tokens,
                "tok_s": round(tokens / elapsed, 2),
                "episodes": episodes,
                "sheds": sheds,
                "failures": failures,
            }
            if latencies:
                entry["p50_ms"] = round(float(np.percentile(latencies, 50)) * 1e3, 1)
                entry["p99_ms"] = round(float(np.percentile(latencies, 99)) * 1e3, 1)
            report[spec["name"]] = entry
            client_dht.shutdown()

    def run_killer():
        delay = args.kill_replica_at * args.multi_duration
        if stop.wait(delay):
            return
        killed["at"] = round(delay, 2)
        print(f"# crash-killing replica 2 at t={delay:.1f}s", file=sys.stderr)
        dht_replica.shutdown()  # the power cord: transport dies, no shutdown

    client_threads = [threading.Thread(target=run_client, args=(spec,)) for spec in specs]
    threads = list(client_threads)
    if args.kill_replica_at and dht_replica is not None:
        threads.append(threading.Thread(target=run_killer))
    for thread in threads:
        thread.start()
    time.sleep(args.multi_duration)
    stop.set()
    for thread in threads:
        thread.join(timeout=60)
    # a client wedged past the join timeout never wrote its report entry, and
    # the verdicts below only inspect entries that exist — a hung client must
    # be a hard failure, not a vacuous pass
    hung = [
        spec["name"] for spec, thread in zip(specs, client_threads)
        if thread.is_alive() or spec["name"] not in report
    ]

    def metric_series(name):
        metric = REGISTRY.get(name)
        if metric is None:
            return {}
        return {",".join(k) or "_": round(c.value, 1) for k, c in metric.series()}

    total_tok_s = round(sum(entry.get("tok_s", 0.0) for entry in report.values()), 2)
    background = [entry for name, entry in report.items() if name != "hot"]
    extra = {
        "clients": report,
        "hot_tok_s": report.get("hot", {}).get("tok_s"),
        "background_tok_s_mean": round(
            sum(e.get("tok_s", 0.0) for e in background) / max(len(background), 1), 2
        ),
        "background_p99_ms_max": max(
            (e.get("p99_ms", 0.0) for e in background), default=None
        ),
        "replicas": args.replicas,
        "killed_replica_at_s": killed["at"],
        "client_rate": args.client_rate,
        "hedges": metric_series("hivemind_moe_hedge_total"),
        "replica_failovers": sum(metric_series("hivemind_moe_replica_failover_total").values()),
        "admission_sheds": sum(metric_series("hivemind_moe_admission_shed_total").values()),
        "layers": num_blocks, "hidden": config.hidden_size,
        "prompt": args.prompt, "generate": args.generate,
        "duration_s": args.multi_duration, "smoke": args.smoke,
    }
    print(json.dumps({
        "metric": "llama_multi_client_decode",
        "value": total_tok_s,
        "unit": "tok/s",
        "extra": extra,
    }))
    # teardown before verdicts so a failing run still cleans up
    for server in (server_primary, server_replica):
        if server is not None:
            server.shutdown()
    for dht in (dht_primary,) + ((dht_replica,) if killed["at"] is None and dht_replica is not None else ()):
        dht.shutdown()

    if hung:
        raise SystemExit(f"client thread(s) hung or unreported (run void): {hung}")
    hard_failures = {
        name: entry["failures"] for name, entry in report.items() if entry["failures"]
    }
    if hard_failures:
        raise SystemExit(f"client-visible request failures (run void): {hard_failures}")
    if args.client_rate and any(entry.get("sheds") for entry in background):
        raise SystemExit(
            "fair-share violated: background clients were shed while the hot "
            f"client saturated its bucket: {report}"
        )
    if not all(entry.get("tokens") for entry in report.values()):
        raise SystemExit(f"a client decoded zero tokens (run void): {report}")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--checkpoint", default=None, help="existing HF-layout dir")
    parser.add_argument("--hidden_dim", type=int, default=1024)
    parser.add_argument("--num_heads", type=int, default=8)
    parser.add_argument("--num_kv_heads", type=int, default=8)
    parser.add_argument("--inner", type=int, default=2816)
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--int8", action="store_true")
    parser.add_argument("--prompt", type=int, default=16)
    parser.add_argument("--generate", type=int, default=48)
    parser.add_argument("--decode_max_len", type=int, default=128)
    parser.add_argument("--activation_compression", default="float16",
                        help="serving wire dtype for the A/B ('none' = "
                             "bit-identical fp32 wire; see docs/benchmarks.md)")
    parser.add_argument("--smoke", action="store_true",
                        help="tier-1-safe regression mode: tiny model, exits "
                             "nonzero if any request fails or the serving "
                             "wire-bytes counters did not move (wired into "
                             "tests so serving data-path breakage fails loudly)")
    parser.add_argument("--multi_client", type=int, default=0,
                        help="skewed multi-tenant mode (ISSUE 13): one HOT client "
                             "decoding flat-out plus this many paced background "
                             "clients, each on its own DHT identity; emits "
                             "per-client tok/s and p99 step latency")
    parser.add_argument("--multi_duration", type=float, default=20.0,
                        help="multi-client mode: traffic window in seconds")
    parser.add_argument("--background_interval", type=float, default=0.08,
                        help="background clients' pause between decode steps")
    parser.add_argument("--replicas", type=int, default=1, choices=(1, 2),
                        help="servers hosting the SAME blocks (replica set "
                             "declared multi-value in the DHT; clients balance, "
                             "hedge and fail over across them)")
    parser.add_argument("--kill_replica_at", type=float, default=0.0,
                        help="crash-kill the second replica at this fraction of "
                             "the multi-client window (0 = never); requires "
                             "--replicas 2. Zero client-visible failures required")
    parser.add_argument("--client_rate", type=float, default=None,
                        help="server-side fair-share admission budget "
                             "(tokens/s per client); the hot client saturates "
                             "its bucket, background clients must be unaffected")
    parser.add_argument("--client_burst", type=float, default=None,
                        help="admission burst ceiling (default 2s of "
                             "--client_rate). Size it to cover the longest "
                             "session re-prefill (prompt+generate): a replica "
                             "death mid-session replays the whole retained "
                             "history in one admission draw, and a burst below "
                             "that sheds the innocent client's recovery")
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()
    apply_platform(args)
    if args.smoke:
        args.hidden_dim, args.num_heads, args.num_kv_heads = 64, 4, 4
        args.inner, args.layers = 128, 1
        args.prompt, args.generate = 4, 4
        args.multi_duration = min(args.multi_duration, 8.0)

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe import RemoteSequential
    from hivemind_tpu.moe.server.llama_loader import load_llama_blocks
    from hivemind_tpu.moe.server.server import Server
    from hivemind_tpu.telemetry.device import (
        COMPILE_TRACKER,
        arm_device_telemetry,
        device_snapshot,
    )

    # device telemetry rides every serving benchmark (ISSUE 19): steady-state
    # decode must never recompile, and the extras carry the compile/transfer
    # summary so bench.py lands it under telemetry.device
    arm_device_telemetry()

    with tempfile.TemporaryDirectory() as tmp:
        if args.checkpoint:
            checkpoint = Path(args.checkpoint)
        else:
            checkpoint = Path(tmp)
            synthesize_checkpoint(
                checkpoint, args.hidden_dim, args.num_heads, args.num_kv_heads,
                args.inner, args.layers,
            )
        if args.multi_client:
            return run_multi_client(args, checkpoint)
        load_start = time.perf_counter()
        backends, config = load_llama_blocks(
            checkpoint, uid_prefix="lb.",
            weight_quantization="int8" if args.int8 else None,
        )
        load_seconds = time.perf_counter() - load_start
        resident_mb = sum(b.param_bytes() for b in backends.values()) / 1e6
        # planning accuracy (VERDICT r3 #8): the capacity planner's input vs reality
        from hivemind_tpu.moe.server.llama_loader import (
            decode_cache_bytes, plan_block_capacity, predict_block_param_bytes,
        )

        predicted_block = predict_block_param_bytes(
            config, "int8" if args.int8 else None
        )
        measured_block = next(iter(backends.values())).param_bytes()
        cache_bytes = decode_cache_bytes(config, batch=1, max_len=args.decode_max_len)
        plan_16gb = plan_block_capacity(
            predicted_block, hbm_bytes=16 * 1024**3,
            decode_sessions=8, cache_bytes_per_session_block=cache_bytes,
        )

        dht = DHT(start=True)
        server = Server(dht, backends, decode_max_len=args.decode_max_len,
                        activation_compression=args.activation_compression)
        client_dht = None
        try:
            server.run_in_background(await_ready=True)
            time.sleep(1.0)
            client_dht = DHT(initial_peers=[str(m) for m in dht.get_visible_maddrs()], start=True)
            pipe = RemoteSequential(client_dht, "lb.", len(backends))

            rng = np.random.RandomState(1)
            hidden = rng.randn(1, args.prompt + args.generate, config.hidden_size).astype(np.float32)
            pipe.decode_step(hidden[:, : args.prompt], "warm", reset=True)  # compile
            pipe.decode_step(hidden[:, args.prompt : args.prompt + 1], "warm")

            # wire accounting (ISSUE 10): serving payload bytes over the timed
            # window, client side only (the server's mirror totals would double
            # count this in-process A/B) — bytes-per-token is the headline the
            # fp16 wire dtype halves vs fp32
            from hivemind_tpu.telemetry import REGISTRY
            from hivemind_tpu.telemetry.serving import SERVING_LEDGER

            def client_wire_bytes():
                out = {}
                for name, field in (("hivemind_moe_bytes_sent_total", "sent"),
                                    ("hivemind_moe_bytes_received_total", "received")):
                    metric = REGISTRY.get(name)
                    if metric is not None:
                        out[field] = metric.labels("client").value
                return out

            wire_before = client_wire_bytes()
            compiles_before = COMPILE_TRACKER.total()
            start = time.perf_counter()
            pipe.decode_step(hidden[:, : args.prompt], "bench", reset=True)
            for t in range(args.generate):
                pos = args.prompt + t
                try:
                    pipe.decode_step(hidden[:, pos : pos + 1], "bench")
                except Exception as e:
                    # ANY failed request voids the run: a tok/s computed over
                    # partially-failed steps would record an inflated A/B
                    raise SystemExit(f"decode step {t} failed (run void): {e!r}")
            elapsed = time.perf_counter() - start
            wire_after = client_wire_bytes()
            wire_delta = {
                key: wire_after.get(key, 0.0) - wire_before.get(key, 0.0)
                for key in wire_after
            }
            # per generated token, each way (the prefill rides the first step)
            wire_per_token = {
                key: round(value / max(args.generate, 1), 1)
                for key, value in wire_delta.items()
            }
            if args.smoke and not all(wire_delta.get(k, 0) > 0 for k in ("sent", "received")):
                raise SystemExit(f"smoke mode: serving wire-bytes counters did not move: {wire_delta}")
            # recompile-storm guard (ISSUE 19): the warm session compiled both
            # the prefill and single-token shapes, so the timed window must be
            # compile-free — a nonzero delta is a silent tok/s regression
            steady_state_compiles = COMPILE_TRACKER.total() - compiles_before
            if args.smoke and steady_state_compiles:
                raise SystemExit(
                    f"smoke mode: {steady_state_compiles} recompile(s) in the "
                    f"steady-state decode window (sites: {COMPILE_TRACKER.counts()})"
                )
            device = device_snapshot()
            # serving attribution rides the artifact (ISSUE 9): the server ran
            # in-process, so the global ledger holds every request's phase
            # decomposition — bench.py lands this under telemetry.serving
            print(json.dumps({
                "metric": "llama_checkpoint_decode",
                "value": round(args.generate / elapsed, 1),
                "unit": "tok/s",
                "extra": {
                    "layers": len(backends), "hidden": config.hidden_size,
                    "inner": config.intermediate_size,
                    "int8": args.int8, "resident_mb": round(resident_mb, 1),
                    "load_seconds": round(load_seconds, 2),
                    "per_block_load_seconds": round(load_seconds / max(len(backends), 1), 2),
                    "predicted_block_mb": round(predicted_block / 1e6, 1),
                    "measured_block_mb": round(measured_block / 1e6, 1),
                    "prediction_error_pct": round(
                        100.0 * abs(predicted_block - measured_block) / max(measured_block, 1), 2
                    ),
                    "planned_blocks_16gb_8sessions": plan_16gb,
                    "prompt": args.prompt, "generated": args.generate,
                    "prefill_included_tok_s": round((args.prompt + args.generate) / elapsed, 1),
                    "activation_compression": args.activation_compression,
                    "smoke": args.smoke,
                    # client-side serving payload bytes over the timed window,
                    # per generated token (the fp16-vs-fp32 wire A/B headline)
                    "wire_bytes_per_token": wire_per_token,
                    "serving": SERVING_LEDGER.summary(),
                    "steady_state_compiles": steady_state_compiles,
                    "device": {
                        "compiles": (device.get("compiles") or {}).get("total", 0),
                        "compile_seconds": (device.get("compiles") or {}).get("seconds", 0.0),
                        "storms": (device.get("compiles") or {}).get("storms", 0),
                        "transfer_bytes": device.get("transfer_bytes"),
                    },
                },
            }))
        finally:
            if client_dht is not None:
                client_dht.shutdown()
            server.shutdown()
            dht.shutdown()


if __name__ == "__main__":
    main()
