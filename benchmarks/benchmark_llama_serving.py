"""Petals-style Llama block serving from a real checkpoint (BASELINE config #5):
synthesizes an HF-layout sharded safetensors checkpoint at the requested shape
(or uses --checkpoint), loads it into llama_block backends (optionally int8
weight-only), serves over RPC, and measures KV-cache decode tok/s through
RemoteSequential."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import tempfile
import time
from pathlib import Path

import numpy as np


def synthesize_checkpoint(path: Path, hidden: int, heads: int, kv_heads: int,
                          inner: int, layers: int) -> None:
    from safetensors.numpy import save_file

    rng = np.random.RandomState(0)
    (path / "config.json").write_text(json.dumps({
        "hidden_size": hidden, "num_attention_heads": heads,
        "num_key_value_heads": kv_heads, "intermediate_size": inner,
        "num_hidden_layers": layers, "rope_theta": 10000.0,
    }))
    head_dim = hidden // heads
    weight_map = {}
    scale = 1.0 / np.sqrt(hidden)
    for layer in range(layers):
        prefix = f"model.layers.{layer}."
        tensors = {
            prefix + "self_attn.q_proj.weight": rng.randn(heads * head_dim, hidden) * scale,
            prefix + "self_attn.k_proj.weight": rng.randn(kv_heads * head_dim, hidden) * scale,
            prefix + "self_attn.v_proj.weight": rng.randn(kv_heads * head_dim, hidden) * scale,
            prefix + "self_attn.o_proj.weight": rng.randn(hidden, hidden) * scale,
            prefix + "mlp.gate_proj.weight": rng.randn(inner, hidden) * scale,
            prefix + "mlp.up_proj.weight": rng.randn(inner, hidden) * scale,
            prefix + "mlp.down_proj.weight": rng.randn(hidden, inner) * scale,
            prefix + "input_layernorm.weight": np.ones(hidden),
            prefix + "post_attention_layernorm.weight": np.ones(hidden),
        }
        shard = f"model-{layer:05d}-of-{layers:05d}.safetensors"
        save_file({k: v.astype(np.float32) for k, v in tensors.items()}, path / shard)
        weight_map.update({name: shard for name in tensors})
    (path / "model.safetensors.index.json").write_text(json.dumps({"weight_map": weight_map}))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--checkpoint", default=None, help="existing HF-layout dir")
    parser.add_argument("--hidden_dim", type=int, default=1024)
    parser.add_argument("--num_heads", type=int, default=8)
    parser.add_argument("--num_kv_heads", type=int, default=8)
    parser.add_argument("--inner", type=int, default=2816)
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--int8", action="store_true")
    parser.add_argument("--prompt", type=int, default=16)
    parser.add_argument("--generate", type=int, default=48)
    parser.add_argument("--decode_max_len", type=int, default=128)
    parser.add_argument("--activation_compression", default="float16",
                        help="serving wire dtype for the A/B ('none' = "
                             "bit-identical fp32 wire; see docs/benchmarks.md)")
    parser.add_argument("--smoke", action="store_true",
                        help="tier-1-safe regression mode: tiny model, exits "
                             "nonzero if any request fails or the serving "
                             "wire-bytes counters did not move (wired into "
                             "tests so serving data-path breakage fails loudly)")
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()
    apply_platform(args)
    if args.smoke:
        args.hidden_dim, args.num_heads, args.num_kv_heads = 64, 4, 4
        args.inner, args.layers = 128, 1
        args.prompt, args.generate = 4, 4

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe import RemoteSequential
    from hivemind_tpu.moe.server.llama_loader import load_llama_blocks
    from hivemind_tpu.moe.server.server import Server

    with tempfile.TemporaryDirectory() as tmp:
        if args.checkpoint:
            checkpoint = Path(args.checkpoint)
        else:
            checkpoint = Path(tmp)
            synthesize_checkpoint(
                checkpoint, args.hidden_dim, args.num_heads, args.num_kv_heads,
                args.inner, args.layers,
            )
        load_start = time.perf_counter()
        backends, config = load_llama_blocks(
            checkpoint, uid_prefix="lb.",
            weight_quantization="int8" if args.int8 else None,
        )
        load_seconds = time.perf_counter() - load_start
        resident_mb = sum(b.param_bytes() for b in backends.values()) / 1e6
        # planning accuracy (VERDICT r3 #8): the capacity planner's input vs reality
        from hivemind_tpu.moe.server.llama_loader import (
            decode_cache_bytes, plan_block_capacity, predict_block_param_bytes,
        )

        predicted_block = predict_block_param_bytes(
            config, "int8" if args.int8 else None
        )
        measured_block = next(iter(backends.values())).param_bytes()
        cache_bytes = decode_cache_bytes(config, batch=1, max_len=args.decode_max_len)
        plan_16gb = plan_block_capacity(
            predicted_block, hbm_bytes=16 * 1024**3,
            decode_sessions=8, cache_bytes_per_session_block=cache_bytes,
        )

        dht = DHT(start=True)
        server = Server(dht, backends, decode_max_len=args.decode_max_len,
                        activation_compression=args.activation_compression)
        client_dht = None
        try:
            server.run_in_background(await_ready=True)
            time.sleep(1.0)
            client_dht = DHT(initial_peers=[str(m) for m in dht.get_visible_maddrs()], start=True)
            pipe = RemoteSequential(client_dht, "lb.", len(backends))

            rng = np.random.RandomState(1)
            hidden = rng.randn(1, args.prompt + args.generate, config.hidden_size).astype(np.float32)
            pipe.decode_step(hidden[:, : args.prompt], "warm", reset=True)  # compile
            pipe.decode_step(hidden[:, args.prompt : args.prompt + 1], "warm")

            # wire accounting (ISSUE 10): serving payload bytes over the timed
            # window, client side only (the server's mirror totals would double
            # count this in-process A/B) — bytes-per-token is the headline the
            # fp16 wire dtype halves vs fp32
            from hivemind_tpu.telemetry import REGISTRY
            from hivemind_tpu.telemetry.serving import SERVING_LEDGER

            def client_wire_bytes():
                out = {}
                for name, field in (("hivemind_moe_bytes_sent_total", "sent"),
                                    ("hivemind_moe_bytes_received_total", "received")):
                    metric = REGISTRY.get(name)
                    if metric is not None:
                        out[field] = metric.labels("client").value
                return out

            wire_before = client_wire_bytes()
            start = time.perf_counter()
            pipe.decode_step(hidden[:, : args.prompt], "bench", reset=True)
            for t in range(args.generate):
                pos = args.prompt + t
                try:
                    pipe.decode_step(hidden[:, pos : pos + 1], "bench")
                except Exception as e:
                    # ANY failed request voids the run: a tok/s computed over
                    # partially-failed steps would record an inflated A/B
                    raise SystemExit(f"decode step {t} failed (run void): {e!r}")
            elapsed = time.perf_counter() - start
            wire_after = client_wire_bytes()
            wire_delta = {
                key: wire_after.get(key, 0.0) - wire_before.get(key, 0.0)
                for key in wire_after
            }
            # per generated token, each way (the prefill rides the first step)
            wire_per_token = {
                key: round(value / max(args.generate, 1), 1)
                for key, value in wire_delta.items()
            }
            if args.smoke and not all(wire_delta.get(k, 0) > 0 for k in ("sent", "received")):
                raise SystemExit(f"smoke mode: serving wire-bytes counters did not move: {wire_delta}")
            # serving attribution rides the artifact (ISSUE 9): the server ran
            # in-process, so the global ledger holds every request's phase
            # decomposition — bench.py lands this under telemetry.serving
            print(json.dumps({
                "metric": "llama_checkpoint_decode",
                "value": round(args.generate / elapsed, 1),
                "unit": "tok/s",
                "extra": {
                    "layers": len(backends), "hidden": config.hidden_size,
                    "inner": config.intermediate_size,
                    "int8": args.int8, "resident_mb": round(resident_mb, 1),
                    "load_seconds": round(load_seconds, 2),
                    "per_block_load_seconds": round(load_seconds / max(len(backends), 1), 2),
                    "predicted_block_mb": round(predicted_block / 1e6, 1),
                    "measured_block_mb": round(measured_block / 1e6, 1),
                    "prediction_error_pct": round(
                        100.0 * abs(predicted_block - measured_block) / max(measured_block, 1), 2
                    ),
                    "planned_blocks_16gb_8sessions": plan_16gb,
                    "prompt": args.prompt, "generated": args.generate,
                    "prefill_included_tok_s": round((args.prompt + args.generate) / elapsed, 1),
                    "activation_compression": args.activation_compression,
                    "smoke": args.smoke,
                    # client-side serving payload bytes over the timed window,
                    # per generated token (the fp16-vs-fp32 wire A/B headline)
                    "wire_bytes_per_token": wire_per_token,
                    "serving": SERVING_LEDGER.summary(),
                },
            }))
        finally:
            if client_dht is not None:
                client_dht.shutdown()
            server.shutdown()
            dht.shutdown()


if __name__ == "__main__":
    main()
