"""Internet-tier transport throughput: encrypted mux stream MB/s between peers over
localhost TCP (the measured justification that the asyncio + Noise-AEAD data path
saturates internet-grade links; the ICI tier handles intra-pod bandwidth — see
docs/design_notes.md and SURVEY §5 two-tier backend).

Modes:
  default            one in-process peer pair, one stream (the historical number)
  --streams k        one pair, k concurrent streams (mux + pipelined AEAD overlap)
  --procs k          one server process + k client processes, each its own stream;
                     prints the AGGREGATE rate. This is the multi-core data-plane
                     measurement (VERDICT r2 #5): with HIVEMIND_AEAD_THREADS > 0 the
                     server unseals the k streams on the AEAD worker pool, so on an
                     m-core host the aggregate scales with min(k, m) until the event
                     loop (framing + protobuf) saturates one core.
  --relay            route the stream through the native C++ relay daemon's splice
  --via-daemon       the CLIENT dials through the native daemon's local DATA-PLANE
                     PROXY ('X' mode): Python ships plaintext frames over loopback
                     and the daemon does the ChaCha20-Poly1305 seal + wire IO in
                     C++ (reference architecture: the whole transport lives in the
                     Go daemon, p2p_daemon.py:84-147). On a one-core host the
                     total cipher work is unchanged (daemon seal + python open
                     share the core), so expect a flat-to-modest delta HERE; the
                     point is the native path exists, is correct, and moves the
                     sender's AEAD out of the Python event loop for multi-core
                     hosts.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import asyncio
import json
import subprocess
import time

import numpy as np


def _payload_mb(mbytes: int) -> np.ndarray:
    return np.random.RandomState(0).randn(mbytes * 1024 * 1024 // 4).astype(np.float32)


async def _add_sink(server):
    from hivemind_tpu.p2p import P2PContext
    from hivemind_tpu.proto import runtime_pb2

    received = []

    async def sink(requests, context: P2PContext):
        total = 0
        async for message in requests:
            for tensor in message.tensors:
                total += len(tensor.buffer)
        received.append(total)
        yield runtime_pb2.ExpertResponse()

    await server.add_protobuf_handler(
        "sink", sink, runtime_pb2.ExpertRequest, stream_input=True, stream_output=True
    )
    return received


async def _stream_once(client, server_peer_id, serialized, chunk_bytes: int) -> float:
    from hivemind_tpu.proto import runtime_pb2
    from hivemind_tpu.compression import split_tensor_for_streaming

    async def requests():
        for chunk in split_tensor_for_streaming(serialized, chunk_bytes):
            yield runtime_pb2.ExpertRequest(uid="bench", tensors=[chunk])

    start = time.perf_counter()
    async for _response in client.iterate_protobuf_handler(
        server_peer_id, "sink", requests(), runtime_pb2.ExpertResponse
    ):
        pass
    return time.perf_counter() - start


async def run_pair(args):
    from hivemind_tpu.p2p import P2P
    from hivemind_tpu.compression import serialize_tensor

    relay_proc = None
    if args.relay or args.via_daemon:
        # spawn the native daemon (relay splice and/or data-plane proxy)
        native = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                              "hivemind_tpu", "native")
        subprocess.run(["make"], cwd=native, check=True, capture_output=True)
        relay_proc = subprocess.Popen(
            [os.path.join(native, "relay_daemon"), "0"], stdout=subprocess.PIPE, text=True
        )
        relay_port = int(relay_proc.stdout.readline().strip().rsplit(" ", 1)[-1])
        relay_proc.stdout.readline()  # identity / encryption-unavailable line

    # --via-daemon covers BOTH directions: the client's outbound dial rides the
    # 'X' proxy and the server registers its public listener with the daemon
    # ('Y'), so seal AND open both run in C++ (reference daemon role parity)
    server = await P2P.create(
        data_proxy_port=relay_port if args.via_daemon else None,
        inbound_data_proxy=args.via_daemon,
    )
    client = await P2P.create(data_proxy_port=relay_port if args.via_daemon else None)
    if args.via_daemon:
        assert server._inbound_proxy_active, "server-side ('Y') registration failed"
    received = await _add_sink(server)

    if args.relay:
        from hivemind_tpu.p2p.relay import RelayClient

        await RelayClient.create(server, "127.0.0.1", relay_port)
        await RelayClient(client, "127.0.0.1", relay_port).dial(server.peer_id)
    else:
        await client.connect(server.get_visible_maddrs()[0])

    serialized = serialize_tensor(_payload_mb(args.mbytes))
    start = time.perf_counter()
    await asyncio.gather(*(
        _stream_once(client, server.peer_id, serialized, args.chunk_kb * 1024)
        for _ in range(args.streams)
    ))
    elapsed = time.perf_counter() - start

    mb = sum(received) / 1e6
    print(json.dumps({
        "metric": "transport_stream_throughput",
        "value": round(mb / elapsed, 1),
        "unit": "MB/s",
        "extra": {
            "payload_mb": round(mb, 1), "seconds": round(elapsed, 3),
            "streams": args.streams,
            "aead_threads": os.environ.get("HIVEMIND_AEAD_THREADS", "auto"),
            "path": ("relay splice + noise AEAD + mux, localhost" if args.relay
                     else "native daemon data-plane proxy BOTH directions "
                     "(client 'X' dial + server 'Y' listener, C++ AEAD) + mux, localhost"
                     if args.via_daemon
                     else "tcp + noise AEAD + mux, localhost"),
        },
    }))
    await client.shutdown()
    await server.shutdown()
    if relay_proc is not None:
        relay_proc.kill()
        relay_proc.wait()


async def run_server_role(args):
    from hivemind_tpu.p2p import P2P

    server = await P2P.create()
    await _add_sink(server)
    print(str(server.get_visible_maddrs()[0]), flush=True)
    await asyncio.get_running_loop().run_in_executor(None, sys.stdin.read)  # until parent closes us
    await server.shutdown()


async def run_client_role(args):
    from hivemind_tpu.p2p import P2P
    from hivemind_tpu.compression import serialize_tensor
    from hivemind_tpu.p2p.peer_id import Multiaddr

    maddr = Multiaddr.parse(args.server_maddr)
    client = await P2P.create()
    await client.connect(maddr)
    serialized = serialize_tensor(_payload_mb(args.mbytes))
    sys.stdout.write("READY\n")
    sys.stdout.flush()
    sys.stdin.readline()  # start barrier: parent releases all clients at once
    elapsed = await _stream_once(client, maddr.peer_id, serialized, args.chunk_kb * 1024)
    print(json.dumps({"seconds": elapsed, "mb": args.mbytes * 1.048576}), flush=True)
    await client.shutdown()


def run_multiproc(args):
    """One server process, k client processes, aggregate MB/s over the joint window."""
    here = os.path.abspath(__file__)
    server = subprocess.Popen(
        [sys.executable, here, "--role", "server"],
        stdout=subprocess.PIPE, stdin=subprocess.PIPE, text=True,
    )
    try:
        maddr = server.stdout.readline().strip()
        assert maddr, "server process failed to start"
        clients = [
            subprocess.Popen(
                [sys.executable, here, "--role", "client", "--server-maddr", maddr,
                 "--mbytes", str(args.mbytes), "--chunk-kb", str(args.chunk_kb)],
                stdout=subprocess.PIPE, stdin=subprocess.PIPE, text=True,
            )
            for _ in range(args.procs)
        ]
        for client in clients:
            assert client.stdout.readline().strip() == "READY"
        start = time.perf_counter()
        for client in clients:
            client.stdin.write("go\n")
            client.stdin.flush()
        results = [json.loads(client.stdout.readline()) for client in clients]
        wall = time.perf_counter() - start
        for client in clients:
            client.wait(timeout=30)
        total_mb = sum(r["mb"] for r in results)
        print(json.dumps({
            "metric": "transport_aggregate_throughput",
            "value": round(total_mb / wall, 1),
            "unit": "MB/s",
            "extra": {
                "client_procs": args.procs, "payload_mb_total": round(total_mb, 1),
                "wall_seconds": round(wall, 3),
                "per_client_mbps": [round(r["mb"] / r["seconds"], 1) for r in results],
                "aead_threads": os.environ.get("HIVEMIND_AEAD_THREADS", "auto"),
                "host_cores": os.cpu_count(),
                "path": "tcp + noise AEAD + mux, localhost, 1 server proc",
            },
        }))
    finally:
        server.stdin.close()
        try:
            server.wait(timeout=10)
        except subprocess.TimeoutExpired:
            server.kill()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mbytes", type=int, default=256)
    parser.add_argument("--chunk-kb", type=int, default=2048,
                        help="streaming part size (clamped to the mux message cap)")
    parser.add_argument("--streams", type=int, default=1,
                        help="concurrent streams over one connection (in-process mode)")
    parser.add_argument("--procs", type=int, default=0,
                        help="client processes against one server process (aggregate mode)")
    parser.add_argument("--via-daemon", action="store_true", dest="via_daemon",
                        help="client dials through the native data-plane proxy")
    parser.add_argument("--relay", action="store_true",
                        help="route through the native relay daemon (circuit splice)")
    parser.add_argument("--role", choices=["server", "client"], help=argparse.SUPPRESS)
    parser.add_argument("--server-maddr", help=argparse.SUPPRESS)
    args = parser.parse_args()
    if args.role == "server":
        asyncio.run(run_server_role(args))
    elif args.role == "client":
        asyncio.run(run_client_role(args))
    elif args.procs > 0:
        run_multiproc(args)
    else:
        asyncio.run(run_pair(args))


if __name__ == "__main__":
    main()
