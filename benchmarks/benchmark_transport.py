"""Internet-tier transport throughput: encrypted mux stream MB/s between two peers
over localhost TCP (the measured justification that the Python asyncio + Noise-AEAD
data path saturates internet-grade links; the ICI tier handles intra-pod bandwidth —
see docs/design_notes.md and SURVEY §5 two-tier backend)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import asyncio
import json
import time

import numpy as np


async def run(args):
    from hivemind_tpu.p2p import P2P, P2PContext
    from hivemind_tpu.proto import runtime_pb2
    from hivemind_tpu.compression import serialize_tensor, split_tensor_for_streaming

    relay_proc = None
    if args.relay:
        # route the stream through the native relay daemon (splice data path)
        import subprocess

        native = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                              "hivemind_tpu", "native")
        subprocess.run(["make"], cwd=native, check=True, capture_output=True)
        relay_proc = subprocess.Popen(
            [os.path.join(native, "relay_daemon"), "0"], stdout=subprocess.PIPE, text=True
        )
        relay_port = int(relay_proc.stdout.readline().strip().rsplit(" ", 1)[-1])

    server = await P2P.create()
    client = await P2P.create()
    received = []

    async def sink(requests, context: P2PContext):
        total = 0
        async for message in requests:
            for tensor in message.tensors:
                total += len(tensor.buffer)
        received.append(total)
        yield runtime_pb2.ExpertResponse()

    await server.add_protobuf_handler(
        "sink", sink, runtime_pb2.ExpertRequest, stream_input=True, stream_output=True
    )
    if args.relay:
        from hivemind_tpu.p2p.relay import RelayClient

        await RelayClient.create(server, "127.0.0.1", relay_port)
        await RelayClient(client, "127.0.0.1", relay_port).dial(server.peer_id)
    else:
        await client.connect(server.get_visible_maddrs()[0])

    payload = np.random.RandomState(0).randn(args.mbytes * 1024 * 1024 // 4).astype(np.float32)
    serialized = serialize_tensor(payload)

    async def requests():
        for chunk in split_tensor_for_streaming(serialized, 2**20):
            yield runtime_pb2.ExpertRequest(uid="bench", tensors=[chunk])

    start = time.perf_counter()
    async for _response in client.iterate_protobuf_handler(
        server.peer_id, "sink", requests(), runtime_pb2.ExpertResponse
    ):
        pass
    elapsed = time.perf_counter() - start

    mb = received[0] / 1e6
    print(json.dumps({
        "metric": "transport_stream_throughput",
        "value": round(mb / elapsed, 1),
        "unit": "MB/s",
        "extra": {
            "payload_mb": round(mb, 1), "seconds": round(elapsed, 3),
            "path": ("relay splice + noise AEAD + mux, localhost" if args.relay
                     else "tcp + noise AEAD + mux, localhost"),
        },
    }))
    await client.shutdown()
    await server.shutdown()
    if relay_proc is not None:
        relay_proc.kill()
        relay_proc.wait()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mbytes", type=int, default=256)
    parser.add_argument("--relay", action="store_true",
                        help="route through the native relay daemon (circuit splice)")
    args = parser.parse_args()
    asyncio.run(run(args))


if __name__ == "__main__":
    main()
