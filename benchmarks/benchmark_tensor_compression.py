"""Per-codec wall time on a 10M-element tensor (parity: reference
benchmarks/benchmark_tensor_compression.py)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import json
import time

import numpy as np


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.devices()

    from hivemind_tpu.compression import CompressionType, deserialize_tensor, serialize_tensor

    tensor = np.random.randn(10_000_000).astype(np.float32)
    results = {}
    for name in ["NONE", "FLOAT16", "MEANSTD_16BIT", "UNIFORM_8BIT", "QUANTILE_8BIT", "BLOCKWISE_8BIT"]:
        ct = getattr(CompressionType, name)
        serialize_tensor(tensor, ct)  # warmup (jit)
        start = time.perf_counter()
        serialized = serialize_tensor(tensor, ct)
        compress_ms = (time.perf_counter() - start) * 1000
        start = time.perf_counter()
        restored = deserialize_tensor(serialized)
        extract_ms = (time.perf_counter() - start) * 1000
        results[name] = {
            "compress_ms": round(compress_ms, 1),
            "extract_ms": round(extract_ms, 1),
            "wire_mb": round(len(serialized.buffer) / 1e6, 2),
            "rel_error": round(float(np.abs(restored - tensor).mean() / np.abs(tensor).mean()), 5),
        }

    print(json.dumps({
        "metric": "compression_throughput_10m",
        "value": results["BLOCKWISE_8BIT"]["compress_ms"],
        "unit": "ms",
        "extra": results,
    }))


if __name__ == "__main__":
    main()
