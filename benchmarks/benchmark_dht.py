"""DHT store/get benchmark (parity: reference benchmarks/benchmark_dht.py — baselines
store 14.9ms/key, get 6.6ms/key at 1024 peers)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_peers", type=int, default=16)
    parser.add_argument("--num_keys", type=int, default=200)
    parser.add_argument("--expiration", type=float, default=300.0)
    parser.add_argument("--max_connections", type=int, default=0,
                        help="per-node connection-manager cap (bounds fds at scale; 0 = unlimited)")
    parser.add_argument("--batch_size", type=int, default=64,
                        help="keys per store_many/get_many call (reference benchmarks batch 64)")
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.devices()

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.utils.timed_storage import get_dht_time

    p2p_opts = {"max_connections": args.max_connections} if args.max_connections else {}
    first = DHT(start=True, **p2p_opts)
    maddrs = [str(m) for m in first.get_visible_maddrs()]
    dhts = [first] + [
        DHT(initial_peers=maddrs, start=True, **p2p_opts)
        for _ in range(args.num_peers - 1)
    ]

    # batched like the reference benchmark (batch 64): one store_many/get_many call
    # runs the per-key beam searches CONCURRENTLY on the node's event loop
    store_ok = get_ok = 0
    batches = [list(range(i, min(i + args.batch_size, args.num_keys)))
               for i in range(0, args.num_keys, args.batch_size)]

    start = time.perf_counter()
    for batch_index, batch in enumerate(batches):
        writer = dhts[batch_index % len(dhts)]
        expiration = get_dht_time() + args.expiration

        async def _store(_dht, node, batch=batch, expiration=expiration):
            return await node.store_many(
                [f"bench_key_{i}" for i in batch], list(batch), expiration
            )

        result = writer.run_coroutine(_store)
        store_ok += sum(bool(v) for v in result.values())
    store_time = time.perf_counter() - start

    start = time.perf_counter()
    for batch_index, batch in enumerate(batches):
        reader = dhts[(batch_index + 7) % len(dhts)]

        async def _get(_dht, node, batch=batch):
            return await node.get_many([f"bench_key_{i}" for i in batch])

        found = reader.run_coroutine(_get)
        get_ok += sum(
            1 for i in batch
            if found.get(f"bench_key_{i}") is not None and found[f"bench_key_{i}"].value == i
        )
    get_time = time.perf_counter() - start

    print(json.dumps({
        "metric": "dht_store_get_latency",
        "value": round(store_time / args.num_keys * 1000, 3),
        "unit": "ms/store",
        "extra": {
            "peers": args.num_peers, "keys": args.num_keys,
            "store_ms": round(store_time / args.num_keys * 1000, 3),
            "get_ms": round(get_time / args.num_keys * 1000, 3),
            "store_success": store_ok / args.num_keys,
            "get_success": get_ok / args.num_keys,
        },
    }))
    for dht in dhts:
        dht.shutdown()


if __name__ == "__main__":
    main()
