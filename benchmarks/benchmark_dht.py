"""DHT store/get benchmark (parity: reference benchmarks/benchmark_dht.py — baselines
store 14.9ms/key, get 6.6ms/key at 1024 peers)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_peers", type=int, default=16)
    parser.add_argument("--num_keys", type=int, default=200)
    parser.add_argument("--expiration", type=float, default=300.0)
    parser.add_argument("--max_connections", type=int, default=0,
                        help="per-node connection-manager cap (bounds fds at scale; 0 = unlimited)")
    parser.add_argument("--batch_size", type=int, default=64,
                        help="keys per store_many/get_many call (reference benchmarks batch 64)")
    parser.add_argument("--declare_storm", action="store_true",
                        help="expert declare-storm mode (ISSUE 13 / ROADMAP item 5 "
                             "follow-up): declare a full expert grid through "
                             "store_many's shared-traversal batching and report "
                             "traversals saved, store RPC count, and leaf recall")
    parser.add_argument("--grid", default="storm.[0:16].[0:16]",
                        help="declare-storm expert grid pattern (all cells declared)")
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.devices()

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.utils.timed_storage import get_dht_time

    if args.declare_storm:
        return declare_storm(args)

    p2p_opts = {"max_connections": args.max_connections} if args.max_connections else {}
    first = DHT(start=True, **p2p_opts)
    maddrs = [str(m) for m in first.get_visible_maddrs()]
    dhts = [first] + [
        DHT(initial_peers=maddrs, start=True, **p2p_opts)
        for _ in range(args.num_peers - 1)
    ]

    # batched like the reference benchmark (batch 64): one store_many/get_many call
    # runs the per-key beam searches CONCURRENTLY on the node's event loop
    store_ok = get_ok = 0
    batches = [list(range(i, min(i + args.batch_size, args.num_keys)))
               for i in range(0, args.num_keys, args.batch_size)]

    start = time.perf_counter()
    for batch_index, batch in enumerate(batches):
        writer = dhts[batch_index % len(dhts)]
        expiration = get_dht_time() + args.expiration

        async def _store(_dht, node, batch=batch, expiration=expiration):
            return await node.store_many(
                [f"bench_key_{i}" for i in batch], list(batch), expiration
            )

        result = writer.run_coroutine(_store)
        store_ok += sum(bool(v) for v in result.values())
    store_time = time.perf_counter() - start

    start = time.perf_counter()
    for batch_index, batch in enumerate(batches):
        reader = dhts[(batch_index + 7) % len(dhts)]

        async def _get(_dht, node, batch=batch):
            return await node.get_many([f"bench_key_{i}" for i in batch])

        found = reader.run_coroutine(_get)
        get_ok += sum(
            1 for i in batch
            if found.get(f"bench_key_{i}") is not None and found[f"bench_key_{i}"].value == i
        )
    get_time = time.perf_counter() - start

    print(json.dumps({
        "metric": "dht_store_get_latency",
        "value": round(store_time / args.num_keys * 1000, 3),
        "unit": "ms/store",
        "extra": {
            "peers": args.num_peers, "keys": args.num_keys,
            "store_ms": round(store_time / args.num_keys * 1000, 3),
            "get_ms": round(get_time / args.num_keys * 1000, 3),
            "store_success": store_ok / args.num_keys,
            "get_success": get_ok / args.num_keys,
        },
    }))
    for dht in dhts:
        dht.shutdown()


def declare_storm(args):
    """Declare every cell of an expert grid (leaf + all prefixes per uid — the
    bulk-republish shape every serving peer emits each update period) and
    surface the PR 12 ``store_many`` shared-traversal batching in the DHT
    benchmark proper: traversals saved, store RPCs issued, wall time, and the
    part that keeps the optimization honest — leaf AND prefix recall read back
    through the real resolution path (the naive version of this batching
    sharded prefix dicts and collapsed recall; the witness fallback is what
    this mode regression-checks at benchmark scale)."""
    import itertools
    import re

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe.server.dht_handler import declare_experts, get_experts
    from hivemind_tpu.telemetry import REGISTRY

    # expand "storm.[0:16].[0:16]" into every grid cell
    blocks = args.grid.split(".")
    dims = []
    for block in blocks[1:]:
        match = re.fullmatch(r"\[(\d+):(\d+)\]", block)
        assert match, f"declare-storm grid blocks must be [lo:hi], got {block!r}"
        dims.append(range(int(match.group(1)), int(match.group(2))))
    uids = [
        ".".join([blocks[0], *map(str, cell)]) for cell in itertools.product(*dims)
    ]

    p2p_opts = {"max_connections": args.max_connections} if args.max_connections else {}
    first = DHT(start=True, **p2p_opts)
    maddrs = [str(m) for m in first.get_visible_maddrs()]
    dhts = [first] + [
        DHT(initial_peers=maddrs, start=True, **p2p_opts)
        for _ in range(args.num_peers - 1)
    ]

    def metric_total(name, label=None):
        metric = REGISTRY.get(name)
        if metric is None:
            return 0.0
        total = 0.0
        for key, child in metric.series():
            if label is None or label in key:
                total += getattr(child, "count", None) or child.value
        return total

    def snapshot():
        return {
            "traversals_saved": metric_total("hivemind_dht_store_traversals_saved_total"),
            "store_rpcs": metric_total("hivemind_dht_rpc_latency_seconds", "store"),
            "find_rpcs": metric_total("hivemind_dht_rpc_latency_seconds", "find"),
        }

    before = snapshot()
    start = time.perf_counter()
    declare_experts(dhts[0], uids, expiration_time=get_dht_time_() + args.expiration)
    declare_seconds = time.perf_counter() - start
    after = snapshot()

    # recall through the real resolution path, from a DIFFERENT peer
    reader = dhts[-1]
    found = get_experts(reader, uids)
    leaf_recall = sum(info is not None for info in found) / len(uids)
    # prefix recall: every first-dimension prefix must resolve its coordinate
    # dict (this is what the witness fallback protects — see dht/node.py)
    async def _prefix_coords(_dht, node):
        prefixes = [blocks[0]] if len(dims) == 1 else [
            f"{blocks[0]}.{i}" for i in dims[0]
        ]
        found = await node.get_many(prefixes)
        ok = 0
        for prefix in prefixes:
            entry = found.get(prefix)
            if entry is not None and isinstance(entry.value, dict) and entry.value:
                ok += 1
        return ok / len(prefixes)

    prefix_recall = reader.run_coroutine(_prefix_coords)

    print(json.dumps({
        "metric": "dht_declare_storm",
        "value": round(len(uids) / declare_seconds, 1),
        "unit": "experts_declared/s",
        "extra": {
            "peers": args.num_peers, "experts": len(uids), "grid": args.grid,
            "declare_seconds": round(declare_seconds, 3),
            "store_traversals_saved": after["traversals_saved"] - before["traversals_saved"],
            "store_rpcs": after["store_rpcs"] - before["store_rpcs"],
            "find_rpcs": after["find_rpcs"] - before["find_rpcs"],
            "leaf_recall": round(leaf_recall, 4),
            "prefix_recall": round(prefix_recall, 4),
        },
    }))
    failures = []
    if leaf_recall < 0.99:
        failures.append(f"leaf recall {leaf_recall}")
    if prefix_recall < 0.99:
        failures.append(f"prefix recall {prefix_recall}")
    for dht in dhts:
        dht.shutdown()
    if failures:
        raise SystemExit(f"declare-storm recall below bar: {failures}")


def get_dht_time_():
    from hivemind_tpu.utils.timed_storage import get_dht_time

    return get_dht_time()


if __name__ == "__main__":
    main()
