"""DHT store/get benchmark (parity: reference benchmarks/benchmark_dht.py — baselines
store 14.9ms/key, get 6.6ms/key at 1024 peers)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_peers", type=int, default=16)
    parser.add_argument("--num_keys", type=int, default=200)
    parser.add_argument("--expiration", type=float, default=300.0)
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.devices()

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.utils.timed_storage import get_dht_time

    first = DHT(start=True)
    maddrs = [str(m) for m in first.get_visible_maddrs()]
    dhts = [first] + [DHT(initial_peers=maddrs, start=True) for _ in range(args.num_peers - 1)]

    store_ok = get_ok = 0
    start = time.perf_counter()
    for i in range(args.num_keys):
        writer = dhts[i % len(dhts)]
        store_ok += bool(writer.store(f"bench_key_{i}", i, get_dht_time() + args.expiration))
    store_time = time.perf_counter() - start

    start = time.perf_counter()
    for i in range(args.num_keys):
        reader = dhts[(i + 7) % len(dhts)]
        result = reader.get(f"bench_key_{i}")
        get_ok += result is not None and result.value == i
    get_time = time.perf_counter() - start

    print(json.dumps({
        "metric": "dht_store_get_latency",
        "value": round(store_time / args.num_keys * 1000, 3),
        "unit": "ms/store",
        "extra": {
            "peers": args.num_peers, "keys": args.num_keys,
            "store_ms": round(store_time / args.num_keys * 1000, 3),
            "get_ms": round(get_time / args.num_keys * 1000, 3),
            "store_success": store_ok / args.num_keys,
            "get_success": get_ok / args.num_keys,
        },
    }))
    for dht in dhts:
        dht.shutdown()


if __name__ == "__main__":
    main()
