"""ICI tier of the two-tier backend: on-mesh reduction + host-boundary staging rate.

Measures one full intra-peer averaging round of `MeshTensorBridge` — per-replica
grads reduced with psum under shard_map (`mesh_mean`), one reduced fp32 copy staged
to the host (`gather_to_host`), and the swarm-averaged result scattered back
(`broadcast_scatter_from_host`) — the exact device↔host path `MeshAverager` runs per
swarm round (averaging/ici.py). On real multi-chip hardware the reduce and the
all-gather ride ICI; under `--platform cpu` with a virtual device mesh this records
the host-emulation rate (a correctness/scaling harness, not an ICI bandwidth claim)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_devices", type=int, default=8)
    parser.add_argument("--num_params", type=int, default=25_000_000)
    parser.add_argument("--num_leaves", type=int, default=8)
    parser.add_argument("--num_rounds", type=int, default=5)
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()
    if args.platform is None:
        args.platform = "cpu"  # virtual-mesh harness by default; pass --platform tpu on a pod

    flags = os.environ.get("XLA_FLAGS", "")
    if args.platform == "cpu" and "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={args.num_devices}"
        ).strip()
    apply_platform(args)

    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hivemind_tpu.parallel import make_mesh
    from hivemind_tpu.parallel.ici import MeshTensorBridge

    n = len(jax.devices())
    mesh = make_mesh(dp=n)
    bridge = MeshTensorBridge(mesh)

    per_leaf = args.num_params // args.num_leaves
    rng = np.random.RandomState(0)
    sharding = NamedSharding(mesh, P("dp"))
    stacked = [
        jax.device_put(rng.randn(n, per_leaf).astype(np.float32), sharding)
        for _ in range(args.num_leaves)
    ]

    # persistent mirrors + streaming per-leaf reduce: steady-state rounds allocate
    # no whole-tree transients (one reduced leaf in flight; VERDICT r3 #4)
    mirrors = bridge.allocate_reduced_mirrors(stacked, reduce_axis="dp")

    def one_round():
        bridge.stage_reduced_into_mirrors(stacked, mirrors, reduce_axis="dp")
        back = bridge.broadcast_scatter_from_host(stacked, mirrors, axis="dp")
        jax.block_until_ready(back)
        return mirrors

    import resource

    host = one_round()  # compile + numerics check
    expected = np.mean(np.asarray(stacked[0]), axis=0)
    np.testing.assert_allclose(host[0], expected, rtol=1e-5, atol=1e-6)

    rss_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6  # GB (linux: KB)
    start = time.perf_counter()
    for _ in range(args.num_rounds):
        one_round()
    elapsed = time.perf_counter() - start
    rss_peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6

    tensor_bytes = per_leaf * args.num_leaves * 4  # what actually moved (// truncates)
    print(json.dumps({
        "metric": "ici_tier_round_rate",
        "value": round(tensor_bytes * args.num_rounds / elapsed / 1e9, 3),
        "unit": "GB/s (reduced fp32 bytes through mesh_mean+gather+scatter)",
        "extra": {
            "devices": n, "params": args.num_params, "leaves": args.num_leaves,
            "rounds": args.num_rounds, "seconds_per_round": round(elapsed / args.num_rounds, 4),
            "backend": jax.default_backend(),
            "model_gb": round(tensor_bytes / 1e9, 3),
            # chunked staging claim (VERDICT r2 weak #3): steady-state rounds must
            # not grow peak RSS by another model copy
            "peak_rss_gb": round(rss_peak, 3),
            "rss_growth_during_rounds_gb": round(rss_peak - rss_before, 3),
        },
    }))


if __name__ == "__main__":
    main()
