"""Per-step overhead of the SliceOptimizer decision broadcast, and what the
skip-count thinning buys (VERDICT r4 next-round #8).

Measures µs/step of `SliceOptimizer.step` on the virtual mesh with a trivial
gradient tree, far from any epoch boundary (the steady-state hot path), for
``max_broadcast_skip`` 0 vs N. On a single process the device broadcast itself
is cheap — the point is the CONTROL-PATH cost (tracker report + decision build +
collective dispatch) that thinning removes; on a real multi-host mesh the
skipped broadcast also removes a host round-trip per step.

Prints one JSON line."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_devices", type=int, default=8)
    parser.add_argument("--steps", type=int, default=400)
    parser.add_argument("--max_broadcast_skip", type=int, default=8)
    parser.add_argument("--no_blackbox", action="store_true",
                        help="skip the spool-armed measurement (ISSUE 17: the "
                             "black-box recorder must not move the hot path "
                             "out of its band)")
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()
    if args.platform is None:
        args.platform = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if args.platform == "cpu" and "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={args.num_devices}"
        ).strip()
    apply_platform(args)

    import jax
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import SliceOptimizer

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))

    def measure(max_skip: int) -> dict:
        opt = SliceOptimizer(
            mesh=mesh,
            params={"w": jax.device_put(np.zeros((8, 128), np.float32), sharding)},
            optimizer=optax.sgd(0.1), dht_factory=lambda: DHT(start=True),
            run_id=f"step_overhead_{max_skip}",
            # huge target: the loop below never reaches a boundary — pure hot path
            target_batch_size=1 << 30, batch_size_per_step=1,
            max_broadcast_skip=max_skip,
        )
        g = {"w": jax.device_put(np.ones((8, 128), np.float32), sharding)}
        try:
            for _ in range(20):  # warm the jits + the step-time EMA
                opt.step(g, batch_size=1)
            # measure the CONTROL PATH alone (grads=None skips the jitted
            # accumulate, whose ~1 ms dispatch would swamp the decision cost)
            start = time.perf_counter()
            skipped = 0
            for _ in range(args.steps):
                if opt._skip_remaining > 0:
                    skipped += 1
                opt.step(None)
            elapsed = time.perf_counter() - start
            return {
                "us_per_step": round(elapsed / args.steps * 1e6, 1),
                "skipped_fraction": round(skipped / args.steps, 3),
            }
        finally:
            opt.shutdown()

    with_broadcast = measure(0)
    thinned = measure(args.max_broadcast_skip)
    spooled = None
    if not args.no_blackbox:
        # same hot path with the flight recorder armed: span finishes now fan
        # out to the spool writer's listener. The append is a buffered msgpack
        # pack + flush off the span's own lock, so the step stays in-band.
        import tempfile

        from hivemind_tpu.telemetry.blackbox import arm_blackbox, disarm_blackbox, read_spool

        with tempfile.TemporaryDirectory(prefix="slice_step_spool_") as spool_dir:
            arm_blackbox(spool_dir, peer="bench", metrics_interval=None)
            try:
                spooled = measure(0)
            finally:
                disarm_blackbox()
            _, spool_stats = read_spool(spool_dir)
            spooled["spool_frames"] = spool_stats["frames"]
    print(json.dumps({
        "metric": "slice_step_decision_overhead_us",
        "value": with_broadcast["us_per_step"],
        "unit": "us/step (broadcast every step)",
        "extra": {
            "thinned_us_per_step": thinned["us_per_step"],
            "thinned_skipped_fraction": thinned["skipped_fraction"],
            "spooled_us_per_step": (spooled or {}).get("us_per_step"),
            "spool_frames": (spooled or {}).get("spool_frames"),
            "max_broadcast_skip": args.max_broadcast_skip,
            "num_devices": args.num_devices,
            "steps": args.steps,
            "note": "single-process mesh: measures the control path; a real "
                    "multi-host mesh additionally saves one host round-trip "
                    "per skipped step",
        },
    }))


if __name__ == "__main__":
    main()
