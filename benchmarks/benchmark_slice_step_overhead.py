"""Per-step overhead of the SliceOptimizer decision broadcast, and what the
skip-count thinning buys (VERDICT r4 next-round #8).

Measures µs/step of `SliceOptimizer.step` on the virtual mesh with a trivial
gradient tree, far from any epoch boundary (the steady-state hot path), for
``max_broadcast_skip`` 0 vs N. On a single process the device broadcast itself
is cheap — the point is the CONTROL-PATH cost (tracker report + decision build +
collective dispatch) that thinning removes; on a real multi-host mesh the
skipped broadcast also removes a host round-trip per step.

Device telemetry (ISSUE 19) is armed for the whole run and doubles as a
regression guard: the steady-state loop must trigger ZERO recompiles after
warmup (a recompile storm here is a silent 1000x step-time bug), and a short
two-peer local-updates probe must produce a nonzero comm/compute overlap
efficiency from real optimizer steps (the ROADMAP item 2 yardstick).

Prints one JSON line."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_devices", type=int, default=8)
    parser.add_argument("--steps", type=int, default=400)
    parser.add_argument("--max_broadcast_skip", type=int, default=8)
    parser.add_argument("--no_blackbox", action="store_true",
                        help="skip the spool-armed measurement (ISSUE 17: the "
                             "black-box recorder must not move the hot path "
                             "out of its band)")
    parser.add_argument("--no_overlap_probe", action="store_true",
                        help="skip the two-peer overlap-efficiency probe "
                             "(ISSUE 19: real optimizer steps must emit a "
                             "nonzero comm/compute overlap ratio)")
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()
    if args.platform is None:
        args.platform = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if args.platform == "cpu" and "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={args.num_devices}"
        ).strip()
    apply_platform(args)

    import threading

    import jax
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import SliceOptimizer
    from hivemind_tpu.telemetry.device import (
        COMPILE_TRACKER,
        STEP_TIMELINE,
        arm_device_telemetry,
        device_snapshot,
    )

    # armed for the whole benchmark: the band below must hold WITH telemetry on
    arm_device_telemetry()

    mesh = Mesh(np.array(jax.devices()).reshape(len(jax.devices())), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))

    def measure(max_skip: int) -> dict:
        opt = SliceOptimizer(
            mesh=mesh,
            params={"w": jax.device_put(np.zeros((8, 128), np.float32), sharding)},
            optimizer=optax.sgd(0.1), dht_factory=lambda: DHT(start=True),
            run_id=f"step_overhead_{max_skip}",
            # huge target: the loop below never reaches a boundary — pure hot path
            target_batch_size=1 << 30, batch_size_per_step=1,
            max_broadcast_skip=max_skip,
        )
        g = {"w": jax.device_put(np.ones((8, 128), np.float32), sharding)}
        try:
            for _ in range(20):  # warm the jits + the step-time EMA
                opt.step(g, batch_size=1)
            # past warmup every compile is a recompile-storm bug: the tracker
            # must not move during the measured loop (ISSUE 19 guard)
            compiles_before = COMPILE_TRACKER.total()
            # measure the CONTROL PATH alone (grads=None skips the jitted
            # accumulate, whose ~1 ms dispatch would swamp the decision cost)
            start = time.perf_counter()
            skipped = 0
            for _ in range(args.steps):
                if opt._skip_remaining > 0:
                    skipped += 1
                opt.step(None)
            elapsed = time.perf_counter() - start
            steady_state_compiles = COMPILE_TRACKER.total() - compiles_before
            assert steady_state_compiles == 0, (
                f"recompile storm in the steady-state loop: {steady_state_compiles} "
                f"compiles after warmup (sites: {COMPILE_TRACKER.counts()})"
            )
            return {
                "us_per_step": round(elapsed / args.steps * 1e6, 1),
                "skipped_fraction": round(skipped / args.steps, 3),
                "steady_state_compiles": steady_state_compiles,
            }
        finally:
            opt.shutdown()

    def measure_overlap() -> dict:
        """Two peers doing REAL optimizer steps (local updates + delayed state
        averaging, the canonical overlapped config): the background averaging
        round must overlap recorded compute, yielding a nonzero ratio."""
        import jax.numpy as jnp

        rng = np.random.RandomState(0)
        features = rng.randn(128, 4).astype(np.float32)
        targets = features @ rng.randn(4).astype(np.float32)

        from hivemind_tpu.optim import Optimizer

        first = DHT(start=True)
        maddrs = [str(m) for m in first.get_visible_maddrs()]
        dhts = [first, DHT(initial_peers=maddrs, start=True)]
        errors = []

        def run_peer(index, dht):
            try:
                opt = Optimizer(
                    dht=dht, run_id="overlap_probe", target_batch_size=32,
                    params={"w": jnp.zeros(4, jnp.float32)}, optimizer=optax.sgd(0.1),
                    batch_size_per_step=16, matchmaking_time=1.0, averaging_timeout=30,
                    average_state_every=1, target_group_size=2, verbose=False,
                    use_local_updates=True, delay_state_averaging=True,
                    tracker_opts=dict(min_refresh_period=0.3, default_refresh_period=0.5),
                )
                loss_grad = jax.jit(jax.value_and_grad(
                    lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2)
                ))
                local = np.random.RandomState(index)
                for _ in range(80):
                    if opt.local_epoch >= 3:
                        break
                    idx = local.choice(len(features), 16)
                    _, grads = loss_grad(opt.params, features[idx], targets[idx])
                    opt.step(grads)
                    time.sleep(0.1)
                opt.shutdown()
            except Exception as e:
                errors.append((index, repr(e)))

        threads = [threading.Thread(target=run_peer, args=(i, d)) for i, d in enumerate(dhts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        for dht in dhts:
            dht.shutdown()
        assert not errors, f"overlap probe peer failures: {errors}"
        summary = STEP_TIMELINE.overlap_summary()
        assert summary.get("rounds"), "no averaging round landed in the step timeline"
        best = max(r["overlap_ratio"] for r in STEP_TIMELINE.records())
        assert best > 0, (
            f"overlap efficiency is zero across {summary['rounds']} round(s): "
            "comm never overlapped recorded compute"
        )
        return {**summary, "best": best, "steps": len(STEP_TIMELINE.steps())}

    with_broadcast = measure(0)
    thinned = measure(args.max_broadcast_skip)
    spooled = None
    if not args.no_blackbox:
        # same hot path with the flight recorder armed: span finishes now fan
        # out to the spool writer's listener. The append is a buffered msgpack
        # pack + flush off the span's own lock, so the step stays in-band.
        import tempfile

        from hivemind_tpu.telemetry.blackbox import arm_blackbox, disarm_blackbox, read_spool

        with tempfile.TemporaryDirectory(prefix="slice_step_spool_") as spool_dir:
            arm_blackbox(spool_dir, peer="bench", metrics_interval=None)
            try:
                spooled = measure(0)
            finally:
                disarm_blackbox()
            _, spool_stats = read_spool(spool_dir)
            spooled["spool_frames"] = spool_stats["frames"]
    overlap = None if args.no_overlap_probe else measure_overlap()
    device = device_snapshot()
    print(json.dumps({
        "metric": "slice_step_decision_overhead_us",
        "value": with_broadcast["us_per_step"],
        "unit": "us/step (broadcast every step)",
        "extra": {
            "thinned_us_per_step": thinned["us_per_step"],
            "thinned_skipped_fraction": thinned["skipped_fraction"],
            "spooled_us_per_step": (spooled or {}).get("us_per_step"),
            "spool_frames": (spooled or {}).get("spool_frames"),
            "max_broadcast_skip": args.max_broadcast_skip,
            "num_devices": args.num_devices,
            "steps": args.steps,
            "steady_state_compiles": with_broadcast["steady_state_compiles"],
            "overlap": overlap,
            "device": {
                "compiles": (device.get("compiles") or {}).get("total", 0),
                "compile_seconds": (device.get("compiles") or {}).get("seconds", 0.0),
                "storms": (device.get("compiles") or {}).get("storms", 0),
                "transfer_bytes": device.get("transfer_bytes"),
            },
            "note": "single-process mesh: measures the control path; a real "
                    "multi-host mesh additionally saves one host round-trip "
                    "per skipped step",
        },
    }))


if __name__ == "__main__":
    main()
