"""The joined two-tier story, measured: an ALBERT MLM model sharded dp×tp×sp over
a device mesh trains as ONE `SliceOptimizer` swarm peer in lockstep with a plain
host-resident `Optimizer` peer — swarm gradient averaging at every epoch, loss
falling on BOTH peers (the v4-32 collaborative-pretraining configuration,
VERDICT r3 next-round #1, rehearsed on a virtual CPU mesh).

Prints one JSON line: epochs/min for the pair plus the slice peer's loss curve
(start/end EMA); optionally dumps a per-step JSONL artifact."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import threading
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_devices", type=int, default=8)
    parser.add_argument("--epochs", type=int, default=20)
    parser.add_argument("--target_batch_size", type=int, default=64)
    parser.add_argument("--batch_size", type=int, default=16, help="per peer per step")
    parser.add_argument("--seq_len", type=int, default=32)
    parser.add_argument("--learning_rate", type=float, default=2e-3)
    parser.add_argument("--metrics_jsonl", default=None)
    parser.add_argument("--delay_grad_averaging", action="store_true",
                        help="overlap the swarm round with training (slice DPU)")
    parser.add_argument("--inject_round_latency", type=float, default=0.0,
                        help="seconds of artificial latency added to every slice "
                             "swarm round (models a slow-sending groupmate); the "
                             "A/B vs --delay_grad_averaging shows epochs/min "
                             "staying flat as this grows")
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()
    if args.platform is None:
        args.platform = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if args.platform == "cpu" and "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={args.num_devices}"
        ).strip()
    apply_platform(args)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.models import (
        AlbertConfig,
        AlbertForMaskedLM,
        make_mlm_loss_fn,
        make_synthetic_mlm_batch,
        make_train_step,
    )
    from hivemind_tpu.optim import Optimizer, SliceOptimizer
    from hivemind_tpu.parallel import make_mesh, params_shardings

    # dp×tp×sp factorization: peel one factor of 2 each for sp and tp, the rest
    # (including odd leftovers) goes to data parallel — works for any device count
    n = args.num_devices
    sp = 2 if n % 2 == 0 else 1
    tp = 2 if (n // sp) % 2 == 0 else 1
    dp = n // (sp * tp)
    assert dp * tp * sp == n, (dp, tp, sp)
    mesh = make_mesh(dp=dp, tp=tp, sp=sp)
    config = AlbertConfig.tiny(mesh=mesh, num_heads=4)
    optimizer = optax.adamw(args.learning_rate)

    # ---- slice peer: sharded params, jitted grads, SliceOptimizer
    model = AlbertForMaskedLM(config)
    loss_fn = make_mlm_loss_fn(model, 0.25)
    sample = make_synthetic_mlm_batch(jax.random.PRNGKey(0), config, args.batch_size, args.seq_len)
    params = model.init(jax.random.PRNGKey(1), sample["input_ids"])["params"]
    params = jax.device_put(params, params_shardings(params, mesh))
    with mesh:
        value_and_grad = jax.jit(jax.value_and_grad(loss_fn))

    boot = DHT(start=True)
    maddrs = [str(m) for m in boot.get_visible_maddrs()]
    matchmaking_time = max(1.5, args.inject_round_latency + 1.5)
    slice_opt = SliceOptimizer(
        mesh=mesh, params=params, optimizer=optimizer, dht_factory=lambda: boot,
        run_id="slice_collab_bench", target_batch_size=args.target_batch_size,
        batch_size_per_step=args.batch_size, target_group_size=2,
        matchmaking_time=matchmaking_time, averaging_timeout=60.0,
        delay_grad_averaging=args.delay_grad_averaging,
    )
    if args.inject_round_latency > 0:
        # every slice round pays the injected latency inside the (blocking or
        # background) averager call; pre-scheduling is disabled so no round can
        # bypass the injection through an already-matched control
        slice_opt._maybe_schedule_gradient_averaging = lambda: None
        real_step = slice_opt.grad_averager.step

        def slow_step(*step_args, **step_kwargs):
            if step_kwargs.get("wait", True):
                time.sleep(args.inject_round_latency)
            return real_step(*step_args, **step_kwargs)

        slice_opt.grad_averager.step = slow_step

    # ---- host peer: same model replicated on one "chip" (plain arrays)
    host_config = AlbertConfig.tiny(num_heads=4)
    host_model, _ = make_train_step(host_config, optimizer, masked_loss_fraction=0.25)
    host_loss_fn = make_mlm_loss_fn(host_model, 0.25)
    host_params = host_model.init(jax.random.PRNGKey(1), sample["input_ids"])["params"]
    host_grad = jax.jit(jax.value_and_grad(host_loss_fn))
    host_dht = DHT(initial_peers=maddrs, start=True)
    host_opt = Optimizer(
        dht=host_dht, run_id="slice_collab_bench", params=host_params,
        optimizer=optimizer, target_batch_size=args.target_batch_size,
        batch_size_per_step=args.batch_size, target_group_size=2,
        matchmaking_time=matchmaking_time, averaging_timeout=60.0,
    )

    stop = threading.Event()
    host_history = []

    def host_loop():
        rng, step_index = jax.random.PRNGKey(7), 0
        while not stop.is_set() and host_opt.local_epoch < args.epochs:
            rng, key = jax.random.split(rng)
            batch = make_synthetic_mlm_batch(key, host_config, args.batch_size, args.seq_len)
            loss, grads = host_grad(host_opt.params, batch)
            host_opt.step(grads, batch_size=args.batch_size)
            host_history.append((step_index, host_opt.local_epoch, float(loss)))
            step_index += 1
            time.sleep(0.05)

    host_thread = threading.Thread(target=host_loop, daemon=True)
    host_thread.start()

    slice_history = []
    sink = open(args.metrics_jsonl, "w") if args.metrics_jsonl else None
    rng = jax.random.PRNGKey(11)
    start = time.perf_counter()
    deadline = start + 1800
    step_index = 0
    try:
        while slice_opt.local_epoch < args.epochs and time.perf_counter() < deadline:
            rng, key = jax.random.split(rng)
            batch = make_synthetic_mlm_batch(key, config, args.batch_size, args.seq_len)
            batch = jax.device_put(batch, NamedSharding(mesh, P("dp", "sp")))
            with mesh:
                loss, grads = value_and_grad(slice_opt.params, batch)
            slice_opt.step(grads, batch_size=args.batch_size)
            record = {"step": step_index, "epoch": slice_opt.local_epoch, "loss": float(loss)}
            slice_history.append(record)
            if sink:
                sink.write(json.dumps(record) + "\n")
            step_index += 1
            time.sleep(0.05)
        # drain a still-pending delayed round so its update lands before shutdown
        drain_deadline = time.perf_counter() + 120
        while getattr(slice_opt, "_pending", None) is not None and time.perf_counter() < drain_deadline:
            slice_opt.step(None)
            time.sleep(0.1)
        elapsed = time.perf_counter() - start
    finally:
        stop.set()
        host_thread.join(timeout=120)
        if sink:
            sink.close()
        slice_opt.shutdown()
        host_opt.shutdown()
        host_dht.shutdown()

    def ema(records, k=8):
        values = [r["loss"] for r in records]
        return sum(values[:k]) / max(len(values[:k]), 1), sum(values[-k:]) / max(len(values[-k:]), 1)

    loss_start, loss_end = ema(slice_history)
    host_end_epoch = host_history[-1][1] if host_history else 0
    print(json.dumps({
        "metric": "slice_collaboration_epochs_per_min",
        "value": round(slice_opt.local_epoch / (elapsed / 60.0), 2),
        "unit": "collaborative epochs/min (slice peer + host peer)",
        "extra": {
            "mesh": {"dp": dp, "tp": tp, "sp": sp},
            "epochs": slice_opt.local_epoch,
            "host_peer_epochs": host_end_epoch,
            "lockstep": abs(slice_opt.local_epoch - host_end_epoch) <= 1,
            "slice_loss_ema_start": round(loss_start, 4),
            "slice_loss_ema_end": round(loss_end, 4),
            "steps": step_index,
            # actual training compute delivered by the slice: the DPU A/B's
            # headline (a stalled mesh shows up here, not in epochs/min)
            "steps_per_min": round(step_index / (elapsed / 60.0), 1),
            "seconds": round(elapsed, 1),
            "target_batch_size": args.target_batch_size,
            "delay_grad_averaging": args.delay_grad_averaging,
            "inject_round_latency": args.inject_round_latency,
        },
    }))


if __name__ == "__main__":
    main()
