#!/usr/bin/env python3
"""Thousand-peer swarm simulator benchmark (ISSUE 12, ROADMAP item 5).

Runs the in-process scenario harness (hivemind_tpu/sim) and prints ONE JSON
line with the scale numbers the BENCH artifact records: peers simulated,
sim-seconds per wall-second, beam-search routing recall@beam vs the
brute-force oracle, and determinism (same seed twice → bit-identical scenario
summaries).

Modes:

- ``--smoke``: tier-1-safe composite (~100 peers total: DHT store/get fan-out
  under churn + link-scoped chaos, matchmaking convergence across a two-region
  partition, beam search over a small grid) plus a same-seed-twice determinism
  double-run of a reduced scenario. Exits nonzero on any failed invariant.
- default (``--scenario soak``): the ROADMAP acceptance config — a 1000-peer
  DHT + matchmaking scenario (seeded churn, bulk republish) run TWICE with the
  same seed to prove bit-identical summaries, plus 10k-expert beam-search
  routing quality with no partitions active (recall@beam must be ≥ 0.95).
- ``--scenario <name>``: one scenario, parameters via flags.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hivemind_tpu.sim import run_scenario, scenario_names  # noqa: E402


def _fail(message: str) -> None:
    print(f"SWARM SIM FAILURE: {message}", file=sys.stderr, flush=True)
    sys.exit(1)


def _check(condition: bool, message: str, failures: list) -> None:
    if not condition:
        failures.append(message)
        print(f"CHECK FAILED: {message}", file=sys.stderr, flush=True)


def run_smoke(seed: int) -> dict:
    failures: list = []
    result = run_scenario("smoke", seed=seed)
    s = result.summary
    _check(s["chaos_link_rule_hits"] > 0, "link-scoped chaos rule never fired", failures)
    _check(s["dht"]["get_success_rate"] >= 0.9, f"dht get success {s['dht']['get_success_rate']}", failures)
    _check(s["dht"]["publish_messages"] > 0, "publish generated no traffic", failures)
    _check(s["beam"]["recall_at_beam"] >= 0.95, f"beam recall {s['beam']['recall_at_beam']}", failures)
    mm = s["matchmaking"]
    _check(mm["groups_during"] > 0, "no groups formed during the partition", failures)
    _check(mm["cross_region_during_settled"] == 0,
           f"{mm['cross_region_during_settled']} cross-region groups formed across a severed link", failures)
    _check(mm["convergence_during"] >= 0.75, f"partition convergence {mm['convergence_during']}", failures)

    # determinism: a reduced scenario twice with one seed → identical digests
    det_params = dict(peers=24, regions=2, keys=40, churn_fraction=0.15, probe_samples=20,
                      matchmaking_peers=6, matchmaking_rounds=1)
    first = run_scenario("dht_churn", seed=seed, **det_params)
    second = run_scenario("dht_churn", seed=seed, **det_params)
    deterministic = first.digest() == second.digest()
    _check(deterministic, "same seed produced different summaries", failures)
    # ISSUE 17: the determinism digest must cover VIRTUAL-TIME telemetry —
    # matchmaking rounds synthesize real allreduce spans, the round ledger
    # aggregates them, and its summary rides the hashed scenario summary
    ledger = (first.summary.get("matchmaking") or {}).get("ledger") or {}
    _check(ledger.get("rounds", 0) > 0, "sim rounds produced no ledger records", failures)

    peers_total = s["dht"]["peers"] + s["beam"]["peers"] + s["matchmaking"]["peers"]
    sim_s = result.diagnostics["sim_seconds"] + first.diagnostics["sim_seconds"] + second.diagnostics["sim_seconds"]
    wall_s = result.diagnostics["wall_seconds"] + first.diagnostics["wall_seconds"] + second.diagnostics["wall_seconds"]
    out = {
        "metric": "swarm_sim_peers",
        "value": peers_total,
        "unit": "peers",
        "extra": {
            "mode": "smoke",
            "seed": seed,
            "deterministic": deterministic,
            "determinism_digest": first.digest()[:16],
            "sim_seconds_per_wall_second": round(sim_s / max(wall_s, 1e-9), 2),
            "recall_at_beam": s["beam"]["recall_at_beam"],
            "get_success_rate": s["dht"]["get_success_rate"],
            "matchmaking_convergence": mm["convergence_during"],
            "chaos_link_rule_hits": s["chaos_link_rule_hits"],
            "ledger": ledger,
            "failures": failures,
        },
    }
    print(json.dumps(out), flush=True)
    if failures:
        _fail("; ".join(failures))
    return out


def run_soak(seed: int, peers: int, experts_grid, beam_size: int, trials: int,
             keys: int = None, churn_fraction: float = 0.10) -> dict:
    """The acceptance config: 1000-peer DHT + matchmaking twice (bit-identical),
    10k-expert beam routing (recall ≥ 0.95, no partitions)."""
    failures: list = []
    soak_params = dict(
        peers=peers, regions=4, keys=keys if keys is not None else peers,
        churn_fraction=churn_fraction, probe_samples=200,
        matchmaking_peers=32, matchmaking_rounds=1,
    )
    first = run_scenario("dht_churn", seed=seed, **soak_params)
    second = run_scenario("dht_churn", seed=seed, **soak_params)
    deterministic = first.digest() == second.digest()
    _check(deterministic, "1k soak: same seed produced different summaries", failures)
    _check(first.summary["get_success_rate"] >= 0.9,
           f"1k soak get success {first.summary['get_success_rate']}", failures)
    _check(first.diagnostics["wall_seconds"] < 300,
           f"1k soak took {first.diagnostics['wall_seconds']}s (budget 300s)", failures)

    beam = run_scenario(
        "beam_routing", seed=seed, peers=100, servers=50,
        grid=tuple(experts_grid), beam_size=beam_size, trials=trials,
    )
    _check(beam.summary["recall_at_beam"] >= 0.95,
           f"recall@beam {beam.summary['recall_at_beam']} < 0.95", failures)

    mm = first.summary.get("matchmaking") or {}
    out = {
        "metric": "swarm_sim_peers",
        "value": peers,
        "unit": "peers",
        "extra": {
            "mode": "soak",
            "seed": seed,
            "deterministic": deterministic,
            "determinism_digest": first.digest()[:16],
            "soak_wall_seconds": first.diagnostics["wall_seconds"],
            "sim_seconds_per_wall_second": first.diagnostics["sim_seconds_per_wall_second"],
            "get_success_rate": first.summary["get_success_rate"],
            "republish_messages": first.summary["republish_messages"],
            "matchmaking_groups": mm.get("groups_formed"),
            "experts": beam.summary["experts"],
            "recall_at_beam": beam.summary["recall_at_beam"],
            "beam_wall_seconds": beam.diagnostics["wall_seconds"],
            # virtual-time round ledger (ISSUE 17): aggregated from the spans
            # the sim's synthesized allreduce rounds emit; part of the digest
            "ledger": mm.get("ledger"),
            "failures": failures,
        },
    }
    print(json.dumps(out), flush=True)
    if failures:
        _fail("; ".join(failures))
    return out


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--scenario", default="soak",
                        choices=["soak", *scenario_names()])
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--smoke", action="store_true", help="tier-1-safe composite + determinism check")
    parser.add_argument("--peers", type=int, default=None,
                        help="peer count (scenario default if omitted; soak: 1000)")
    parser.add_argument("--grid", type=int, nargs="+", default=[10, 10, 100])
    parser.add_argument("--beam_size", type=int, default=8)
    parser.add_argument("--trials", type=int, default=8)
    parser.add_argument("--keys", type=int, default=None)
    parser.add_argument("--churn_fraction", type=float, default=0.1)
    args = parser.parse_args()

    if args.smoke or args.scenario == "smoke":
        # the composite's invariants must always be checked (nonzero exit on
        # any failure) — the generic path below would skip them
        run_smoke(args.seed)
        return
    if args.scenario == "soak":
        run_soak(args.seed, args.peers if args.peers is not None else 1000,
                 args.grid, args.beam_size, args.trials,
                 keys=args.keys, churn_fraction=args.churn_fraction)
        return

    # single-scenario paths: honor every supplied flag, fall back to the
    # scenario's own defaults when a flag is omitted
    params = {}
    if args.scenario == "dht_churn":
        peers = args.peers if args.peers is not None else 1000
        params = dict(peers=peers, keys=args.keys if args.keys is not None else peers,
                      churn_fraction=args.churn_fraction)
    elif args.scenario == "beam_routing":
        params = dict(grid=tuple(args.grid), beam_size=args.beam_size, trials=args.trials)
        if args.peers is not None:
            params["peers"] = args.peers
    elif args.scenario == "matchmaking_partition":
        if args.peers is not None:
            params["peers"] = args.peers
    result = run_scenario(args.scenario, seed=args.seed, **params)
    print(json.dumps({
        "metric": f"swarm_sim_{args.scenario}",
        "value": result.summary.get("peers"),
        "unit": "peers",
        "extra": {"summary": result.summary, "diagnostics": result.diagnostics,
                  "digest": result.digest()[:16]},
    }), flush=True)


if __name__ == "__main__":
    main()
