"""Butterfly all-reduce benchmark (parity: reference benchmarks/benchmark_averaging.py
— 16 peers, groups of 4, ~8.6M params). Reports rounds, success rate, and the driver
north-star: effective GB/s per peer."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import time

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_peers", type=int, default=8)
    parser.add_argument("--target_group_size", type=int, default=4)
    parser.add_argument("--num_rounds", type=int, default=3)
    parser.add_argument("--num_params", type=int, default=1_000_000)
    parser.add_argument("--compression", default="FLOAT16",
                        help="wire codec: a CompressionType name (FLOAT16, NONE, ...) or a "
                             "wire-tier alias (none/float16/uniform8/blockwise8, case-"
                             "insensitive). The 8-bit tiers negotiate per-link error "
                             "feedback automatically (ISSUE 11)")
    parser.add_argument("--part_size_bytes", type=int, default=None,
                        help="pre-compression part size (default: the library default, "
                             "2 MiB — measured fastest on loopback; clamped to the mux cap)")
    parser.add_argument("--min_matchmaking_time", type=float, default=2.0,
                        help="leader's group-collection window; on loopback the group "
                             "fills (and begins early) well before 1s, so the floor is "
                             "pure overhead — lower it when benchmarking bandwidth")
    parser.add_argument("--simulated_link_mbps", type=float, default=None,
                        help="throttle every tensor-part/delta payload to this per-link "
                             "bandwidth via the chaos engine's byte-proportional `throttle` "
                             "action — the WAN regime the quantized tiers exist for. "
                             "Unthrottled loopback is latency-bound, so wire-codec wins "
                             "are only representative under a link budget")
    parser.add_argument("--smoke", action="store_true",
                        help="tier-1-safe regression mode: tiny swarm + payload, exits "
                             "nonzero unless every round succeeds (wired into tests so "
                             "throughput-path breakage fails loudly)")
    args = parser.parse_args()
    if args.smoke:
        args.num_peers, args.target_group_size = 2, 2
        args.num_rounds, args.num_params = 1, 10_000
        args.min_matchmaking_time = 0.5

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.devices()

    from hivemind_tpu.averaging import DecentralizedAverager
    from hivemind_tpu.compression import CompressionType, get_codec
    from hivemind_tpu.dht import DHT
    from hivemind_tpu.telemetry import LEDGER, REGISTRY, watchdog_summary

    first = DHT(start=True)
    maddrs = [str(m) for m in first.get_visible_maddrs()]
    dhts = [first] + [DHT(initial_peers=maddrs, start=True) for _ in range(args.num_peers - 1)]
    # wire-tier aliases (uniform8 etc.) map onto the enum; enum names pass through
    tier_aliases = {"none": "NONE", "float16": "FLOAT16", "uniform8": "UNIFORM_8BIT",
                    "blockwise8": "BLOCKWISE_8BIT", "meanstd16": "MEANSTD_16BIT",
                    "quantile8": "QUANTILE_8BIT"}
    compression_name = tier_aliases.get(args.compression.lower(), args.compression.upper())
    codec = get_codec(getattr(CompressionType, compression_name))
    if args.simulated_link_mbps:
        from hivemind_tpu.resilience import CHAOS

        rate_bytes_s = args.simulated_link_mbps * 125_000.0
        CHAOS.add_rule("allreduce.load", "throttle", rate=rate_bytes_s)
        CHAOS.add_rule("allreduce.reduce", "throttle", rate=rate_bytes_s)
    averager_kwargs = {}
    if args.part_size_bytes is not None:
        averager_kwargs["part_size_bytes"] = args.part_size_bytes
    averagers = []
    for i, dht in enumerate(dhts):
        rng = np.random.RandomState(i)
        tensors = [rng.randn(args.num_params).astype(np.float32)]
        averagers.append(
            DecentralizedAverager(
                tensors, dht, prefix="bench", start=True,
                target_group_size=args.target_group_size,
                min_matchmaking_time=args.min_matchmaking_time, compression=codec,
                initial_group_bits="" if args.num_peers <= args.target_group_size else "0",
                **averager_kwargs,
            )
        )

    successes = attempts = 0
    start = time.perf_counter()
    for round_index in range(args.num_rounds):
        controls = [a.step(wait=False, timeout=60) for a in averagers]
        for control in controls:
            attempts += 1
            try:
                control.result(timeout=90)
                successes += 1
            except Exception:
                pass
    elapsed = time.perf_counter() - start

    bytes_per_peer_round = args.num_params * 4 * 2  # send + receive one vector's worth
    gbps_per_peer = bytes_per_peer_round * args.num_rounds / elapsed / 1e9
    print(json.dumps({
        "metric": "averaging_gbps_per_peer",
        "value": round(gbps_per_peer, 4),
        "unit": "GB/s/peer",
        "extra": {
            "peers": args.num_peers, "rounds": args.num_rounds,
            "params": args.num_params, "success_rate": successes / max(attempts, 1),
            "compression": compression_name.lower(),
            "simulated_link_mbps": args.simulated_link_mbps,
            "seconds_per_round": round(elapsed / args.num_rounds, 3),
            # the registry saw every matchmaking/all-reduce/DHT event of this
            # swarm: embed it so BENCH artifacts carry the per-phase breakdown
            # (VERDICT r5: five rounds of artifacts had none)
            "telemetry": REGISTRY.snapshot(),
            # per-round attribution (ISSUE 8): rounds, mean/p95 phase durations
            # and straggler scores from the ledger, plus event-loop stall count
            # and max lag — a regressed headline number then names its cause
            "attribution": {"ledger": LEDGER.summary(), "watchdog": watchdog_summary()},
        },
    }))
    for averager in averagers:
        averager.shutdown()
    for dht in dhts:
        dht.shutdown()
    if args.smoke and successes != attempts:
        sys.exit(f"smoke mode: only {successes}/{attempts} averaging steps succeeded")


if __name__ == "__main__":
    main()
