"""Collaborative optimizer harness (parity: reference benchmarks/benchmark_optimizer.py
— MLP peers, target_batch_size epochs, convergence check)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import argparse
import json
import threading
import time

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_peers", type=int, default=2)
    parser.add_argument("--target_batch_size", type=int, default=128)
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--max_epochs", type=int, default=4)
    parser.add_argument("--hidden", type=int, default=64)
    mode = parser.add_mutually_exclusive_group()
    mode.add_argument("--dpu", action="store_true",
                      help="Delayed Parameter Updates: epoch transitions run in the "
                           "background, training continues during averaging")
    mode.add_argument("--local_updates", action="store_true",
                      help="async local-SGD: apply every step locally, average state "
                           "in the background with the delta rule so concurrent "
                           "steps survive")
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()
    if args.platform is None:
        args.platform = "cpu"
    apply_platform(args)
    import jax

    jax.devices()

    import jax.numpy as jnp
    import optax

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import Optimizer

    rng = np.random.RandomState(0)
    true_w = rng.randn(args.hidden).astype(np.float32)
    X = rng.randn(1024, args.hidden).astype(np.float32)
    y = X @ true_w

    @jax.jit
    def loss_and_grad(params, xx, yy):
        fn = lambda p: jnp.mean((xx @ p["w"] - yy) ** 2)
        return jax.value_and_grad(fn)(params)

    first = DHT(start=True)
    maddrs = [str(m) for m in first.get_visible_maddrs()]
    dhts = [first] + [DHT(initial_peers=maddrs, start=True) for _ in range(args.num_peers - 1)]
    results = {}

    def peer_loop(index):
        mode_opts = {}
        if args.dpu:
            mode_opts["delay_optimizer_step"] = True
        if args.local_updates:
            # the canonical local-SGD combination (optim/optimizer.py docstring):
            # background state averaging + delta rule to protect concurrent steps
            mode_opts.update(
                use_local_updates=True, delta_rule_averaging=True, delay_state_averaging=True
            )
        opt = Optimizer(
            dht=dhts[index], run_id="bench_opt", target_batch_size=args.target_batch_size,
            params={"w": jnp.zeros(args.hidden)}, optimizer=optax.sgd(0.2),
            batch_size_per_step=args.batch_size, matchmaking_time=1.5,
            target_group_size=args.num_peers,
            tracker_opts=dict(min_refresh_period=0.3), **mode_opts,
        )
        local = np.random.RandomState(index)
        first_loss = last_loss = None
        steps = 0
        while opt.local_epoch < args.max_epochs and steps < 200:
            idx = local.choice(len(X), args.batch_size)
            loss, grads = loss_and_grad(opt.params, X[idx], y[idx])
            first_loss = first_loss if first_loss is not None else float(loss)
            last_loss = float(loss)
            opt.step(grads)
            steps += 1
            time.sleep(0.2)
        results[index] = (first_loss, last_loss, opt.local_epoch)
        opt.shutdown()

    start = time.perf_counter()
    threads = [threading.Thread(target=peer_loop, args=(i,)) for i in range(args.num_peers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - start

    print(json.dumps({
        "metric": "optimizer_loss_reduction",
        "value": round(min(r[0] / max(r[1], 1e-9) for r in results.values()), 2),
        "unit": "x",
        "extra": {
            "peers": args.num_peers, "seconds": round(elapsed, 1),
            "mode": "dpu" if args.dpu else ("local_updates" if args.local_updates else "sync"),
            "per_peer": {str(k): {"first": round(v[0], 4), "last": round(v[1], 4), "epoch": v[2]} for k, v in results.items()},
        },
    }))
    for dht in dhts:
        dht.shutdown()


if __name__ == "__main__":
    main()
