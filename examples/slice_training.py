"""Multi-host slice training: a whole TPU slice (several hosts, one jax process
each) trains as ONE swarm peer.

This is the end-to-end recipe for the two-tier communication backend
(SURVEY §5, docs/design_notes.md "multi-host slices"):

- every process runs the SAME jitted train step over the shared ``Mesh`` —
  gradients ride ICI via pjit/shard_map exactly as in any SPMD program;
- the model averages with the REST OF THE SWARM (other slices, GPU peers,
  volunteer laptops) through :class:`SliceAverager`: process 0 alone talks to the
  DHT/matchmaking/all-reduce, the other hosts join only mesh collectives.

The flow is the local-SGD family (reference use_local_updates): local optax steps
between swarm rounds, parameters averaged every ``--steps_per_round``.

Launch one process per host, e.g. a 2-process CPU rehearsal of a v4-32 topology:

    python examples/slice_training.py --platform cpu --devices_per_proc 4 \
        --num_processes 2 --process_id 0 --coordinator 127.0.0.1:9911 &
    python examples/slice_training.py --platform cpu --devices_per_proc 4 \
        --num_processes 2 --process_id 1 --coordinator 127.0.0.1:9911

Process 0 additionally accepts ``--initial_peers`` (the swarm to join) and
prints its own DHT address for others. On a real slice drop --devices_per_proc
(the chips are discovered) and run one process per host.
"""

from __future__ import annotations

import argparse
import os


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--run_id", default="slice_demo")
    parser.add_argument("--coordinator", default=None,
                        help="host:port of process 0 for jax.distributed.initialize "
                             "(omit for single-process)")
    parser.add_argument("--num_processes", type=int, default=1)
    parser.add_argument("--process_id", type=int, default=0)
    parser.add_argument("--devices_per_proc", type=int, default=0,
                        help=">0: force that many virtual CPU devices (rehearsal)")
    parser.add_argument("--initial_peers", nargs="*", default=[],
                        help="swarm bootstrap (used by process 0 only)")
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--steps_per_round", type=int, default=20)
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--dim", type=int, default=64)
    parser.add_argument("--learning_rate", type=float, default=0.05)
    parser.add_argument("--target_group_size", type=int, default=2)
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()
    if args.devices_per_proc > 0:
        # replace (not prepend) any inherited device-count flag: with duplicates
        # XLA honors the last one, so an inherited value would win
        kept = [
            flag for flag in os.environ.get("XLA_FLAGS", "").split()
            if not flag.startswith("--xla_force_host_platform_device_count")
        ]
        os.environ["XLA_FLAGS"] = " ".join(
            kept + [f"--xla_force_host_platform_device_count={args.devices_per_proc}"]
        )
    apply_platform(args)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    if args.coordinator:
        jax.distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )

    from hivemind_tpu.averaging import SliceAverager
    from hivemind_tpu.dht import DHT
    from hivemind_tpu.utils.logging import get_logger

    logger = get_logger(f"slice_trainer.p{jax.process_index()}")

    devices = np.array(jax.devices())
    mesh = Mesh(devices.reshape(-1), ("dp",))
    logger.info(f"mesh: {devices.size} devices across {jax.process_count()} processes")

    # a toy regression model, dp-sharded batch, replicated params — the slice's
    # ICI carries the gradient psum exactly as a real model's would
    rng = np.random.RandomState(0)  # SAME init on every process (replicated params)
    params = {
        "w": jax.device_put(
            rng.randn(args.dim, args.dim).astype(np.float32) * 0.1,
            NamedSharding(mesh, P()),
        ),
        "b": jax.device_put(np.zeros(args.dim, np.float32), NamedSharding(mesh, P())),
    }
    target_w = np.eye(args.dim, dtype=np.float32)  # learn the identity map

    optimizer = optax.adam(args.learning_rate)
    opt_state = optimizer.init(params)

    @jax.jit
    def train_step(params, opt_state, x, y):
        def loss_fn(p):
            pred = x @ p["w"] + p["b"]
            return jnp.mean((pred - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def dht_factory():
        dht = DHT(initial_peers=args.initial_peers, start=True)
        for maddr in dht.get_visible_maddrs():
            logger.info(f"swarm members can join via: --initial_peers {maddr}")
        return dht

    slice_avg = SliceAverager(
        params, mesh, dht_factory,
        prefix=f"{args.run_id}_params", start=True,
        target_group_size=args.target_group_size, min_matchmaking_time=1.0,
    )

    batch_sharding = NamedSharding(mesh, P("dp"))
    data_rng = np.random.RandomState(100 + jax.process_index())
    assert args.batch_size % jax.process_count() == 0, (
        f"batch_size {args.batch_size} must divide evenly across "
        f"{jax.process_count()} processes"
    )
    local_rows = args.batch_size // jax.process_count()
    assert local_rows and local_rows % len(mesh.local_devices) == 0, (
        "per-process batch must tile the local devices"
    )
    global_shape = (args.batch_size, args.dim)
    for step in range(1, args.steps + 1):
        # each process feeds ITS OWN rows of the global batch (data parallelism
        # across hosts); the global array is assembled from process-local shards
        x_host = data_rng.randn(local_rows, args.dim).astype(np.float32)
        y_host = x_host @ target_w
        x = jax.make_array_from_process_local_data(batch_sharding, x_host, global_shape)
        y = jax.make_array_from_process_local_data(batch_sharding, y_host, global_shape)
        params, opt_state, loss = train_step(params, opt_state, x, y)
        if step % args.steps_per_round == 0:
            slice_avg.device_tree = params
            ok = slice_avg.step(timeout=30)
            if ok:
                params = slice_avg.device_tree
                # adam moments describe the pre-average trajectory; restarting
                # them after adopting the swarm average is the stable choice for
                # this demo (delta-rule integration lives in the full Optimizer)
                opt_state = optimizer.init(params)
            logger.info(f"step {step} loss {float(loss):.5f} swarm_round_ok={ok}")
        elif step % 10 == 0:
            logger.info(f"step {step} loss {float(loss):.5f}")

    final = float(loss)
    logger.info(f"done: final loss {final:.5f}")
    slice_avg.shutdown()
    print(f"FINAL_LOSS {jax.process_index()} {final}", flush=True)


if __name__ == "__main__":
    main()
