"""Pipelined inference over the swarm (Petals-style demo).

Serve a model's transformer blocks on any mix of peers, then chain them from a
client — each block possibly on a different machine, with DHT-based failover:

    # peer 1: host blocks 0 and 2 (prints the maddr to join)
    python examples/pipeline_inference.py --serve blk.0 blk.2

    # peer 2: host block 1
    python examples/pipeline_inference.py --serve blk.1 --initial_peers /ip4/…

    # anyone: run the pipeline
    python examples/pipeline_inference.py --num_blocks 3 --initial_peers /ip4/…
"""

from __future__ import annotations

import argparse
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--serve", nargs="*", default=None, help="block uids to host (server mode)")
    parser.add_argument("--expert_cls", default="transformer",
                        help="block class to serve; use causal_transformer or llama_block "
                             "(RMSNorm+RoPE+GQA+SwiGLU, the Petals-style Llama shape) "
                             "for --generate")
    parser.add_argument("--expert_kwargs", default=None,
                        help="JSON dict forwarded to the block class, e.g. "
                             "'{\"num_kv_heads\": 2}' for GQA llama_block")
    parser.add_argument("--no_sessions", action="store_true",
                        help="decode via right-padded full recompute instead of "
                             "KV-cache sessions")
    parser.add_argument("--generate", type=int, default=0,
                        help="greedy-decode this many tokens through the pipeline "
                             "(requires causal_transformer blocks)")
    parser.add_argument("--vocab_size", type=int, default=128)
    parser.add_argument("--prefix", default="blk.")
    parser.add_argument("--num_blocks", type=int, default=3)
    parser.add_argument("--hidden_dim", type=int, default=64)
    parser.add_argument("--seq_len", type=int, default=64)
    parser.add_argument("--batch_size", type=int, default=4)
    parser.add_argument("--initial_peers", nargs="*", default=[])
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()
    apply_platform(args)

    import jax.numpy as jnp
    import numpy as np
    import optax

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe import RemoteSequential, Server
    from hivemind_tpu.utils.logging import get_logger

    logger = get_logger("pipeline_demo")

    if args.serve:
        dht = DHT(initial_peers=args.initial_peers, start=True)
        for maddr in dht.get_visible_maddrs():
            logger.info(f"to join: --initial_peers {maddr}")
        import json

        server = Server.create(
            expert_uids=list(args.serve), expert_cls=args.expert_cls,
            hidden_dim=args.hidden_dim, dht=dht, start=True,
            expert_kwargs=json.loads(args.expert_kwargs) if args.expert_kwargs else None,
            optim_factory=lambda: optax.sgd(1e-4),
        )
        logger.info(f"serving blocks {args.serve}; ctrl-c to stop")
        try:
            while True:
                time.sleep(5)
        except KeyboardInterrupt:
            server.shutdown()
            dht.shutdown()
        return

    assert args.initial_peers, "client mode needs --initial_peers of a serving swarm"
    dht = DHT(initial_peers=args.initial_peers, start=True)
    pipe = RemoteSequential(dht, args.prefix, args.num_blocks)

    if args.generate:
        # Petals-style autoregressive decode: embedding + tied lm head live on the
        # CLIENT; the transformer stack runs remotely as causal blocks. KV-cache
        # decode sessions on each serving peer make every step O(context): one
        # prefill with the prompt, then one single-token RPC chain per token
        # (--no_sessions falls back to the right-padded full recompute, which
        # causality makes exact at the fixed schema length).
        import uuid

        rng = np.random.RandomState(0)
        embedding = jnp.asarray(rng.randn(args.vocab_size, args.hidden_dim) * 0.05, jnp.float32)
        tokens = [1]  # BOS
        start = time.perf_counter()
        if args.no_sessions:
            context = 64
            for _ in range(args.generate):
                window = tokens[-context:]
                ids = np.zeros(context, np.int64)
                ids[: len(window)] = window
                hidden = embedding[jnp.asarray(ids)][None]  # [1, 64, hid]
                hidden = pipe(hidden)
                logits = hidden[0, len(window) - 1] @ embedding.T  # tied head
                tokens.append(int(jnp.argmax(logits)))
        else:
            session = uuid.uuid4().hex
            hidden = np.asarray(embedding[jnp.asarray(tokens)])[None]  # prompt [1, 1, hid]
            out = pipe.decode_step(hidden, session, reset=True)
            # re-prefill window when a session hits its capacity: half of the
            # advertised per-session cache so a restarted session has headroom
            capacity = pipe.decode_capacity() or 128
            window = max(1, min(64, capacity // 2))
            for remaining in range(args.generate, 0, -1):
                logits = jnp.asarray(out[0, -1]) @ embedding.T
                tokens.append(int(jnp.argmax(logits)))
                if remaining == 1:
                    break  # the last token needs no further step
                try:
                    step = np.asarray(embedding[jnp.asarray(tokens[-1:])])[None]
                    out = pipe.decode_step(step, session)
                except Exception:
                    # session capacity (server --decode_max_len) reached: restart a
                    # fresh session prefilled with the recent token window — the
                    # same sliding-context approximation --no_sessions uses
                    pipe.close_decode_session(session)
                    session = uuid.uuid4().hex
                    recent = tokens[-window:]
                    hidden = np.asarray(embedding[jnp.asarray(recent)])[None]
                    out = pipe.decode_step(hidden, session, reset=True)
        elapsed = time.perf_counter() - start
        mode = "right-padded recompute" if args.no_sessions else "KV-cache sessions"
        logger.info(
            f"generated {args.generate} tokens through {args.num_blocks} remote blocks "
            f"in {elapsed:.2f}s ({args.generate / elapsed:.1f} tok/s, {mode}, "
            f"untrained weights): {tokens}"
        )
        dht.shutdown()
        return
    x = jnp.asarray(
        np.random.RandomState(0).randn(args.batch_size, args.seq_len, args.hidden_dim),
        jnp.float32,
    )
    start = time.perf_counter()
    out = pipe(x)
    out.block_until_ready()
    elapsed = time.perf_counter() - start
    logger.info(
        f"pipeline of {args.num_blocks} remote blocks: {x.shape} -> {out.shape} "
        f"in {elapsed:.2f}s (|out| = {float(jnp.linalg.norm(out)):.2f})"
    )
    dht.shutdown()


if __name__ == "__main__":
    main()
