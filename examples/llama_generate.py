"""Generate tokens from a real Llama checkpoint served over the swarm
(BASELINE config #5 end-to-end — the Petals usage shape).

Server(s): each hosts a range of the checkpoint's decoder layers

    python examples/llama_generate.py --checkpoint /path/to/hf_llama \
        --serve 0:16 --int8                      # prints the maddr to join
    python examples/llama_generate.py --checkpoint /path/to/hf_llama \
        --serve 16:32 --int8 --initial_peers /ip4/…

Client: keeps only the embedding + final norm + LM head locally

    python examples/llama_generate.py --checkpoint /path/to/hf_llama \
        --generate 64 --prompt_ids 1 15043 3186 --initial_peers /ip4/…

Token ids in, token ids out (tokenizers are orthogonal — pipe ids through any
HF tokenizer where one is available on disk)."""

from __future__ import annotations

import argparse
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--checkpoint", required=True, help="HF-layout Llama dir")
    parser.add_argument("--serve", default=None,
                        help="'start:stop' layer range to host (server mode); "
                             "omit for client mode")
    parser.add_argument("--int8", action="store_true",
                        help="serve int8 weight-only (4x less resident HBM)")
    parser.add_argument("--uid_prefix", default="llama.")
    parser.add_argument("--initial_peers", nargs="*", default=[])
    parser.add_argument("--generate", type=int, default=32)
    parser.add_argument("--prompt_ids", type=int, nargs="*", default=[1],
                        help="prompt token ids (default: BOS only)")
    parser.add_argument("--decode_max_len", type=int, default=512)
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()
    apply_platform(args)

    import numpy as np

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.moe.server.llama_loader import (
        LlamaCheckpointConfig,
        LlamaClientHead,
        generate_greedy,
        load_llama_blocks,
    )
    from hivemind_tpu.utils.logging import get_logger

    logger = get_logger("llama_generate")
    config = LlamaCheckpointConfig.load(args.checkpoint)

    if args.serve is not None:
        from hivemind_tpu.moe.server.server import Server

        start, _, stop = args.serve.partition(":")
        layers = range(int(start or 0), int(stop or config.num_hidden_layers))
        backends, _config = load_llama_blocks(
            args.checkpoint, layers=layers, uid_prefix=args.uid_prefix,
            weight_quantization="int8" if args.int8 else None,
        )
        dht = DHT(initial_peers=args.initial_peers, start=True)
        server = Server(dht, backends, decode_max_len=args.decode_max_len)
        server.run_in_background(await_ready=True)
        for maddr in dht.get_visible_maddrs():
            logger.info(f"serving layers {layers.start}:{layers.stop}; join via --initial_peers {maddr}")
        try:
            while True:
                time.sleep(60)
        except KeyboardInterrupt:
            server.shutdown()
            dht.shutdown()
        return

    from hivemind_tpu.moe import RemoteSequential

    dht = DHT(initial_peers=args.initial_peers, start=True)
    head = LlamaClientHead.load(args.checkpoint)
    pipe = RemoteSequential(dht, args.uid_prefix, config.num_hidden_layers)
    prompt = np.asarray([args.prompt_ids], np.int64)
    logger.info(
        f"generating {args.generate} tokens through {config.num_hidden_layers} "
        f"remote layers (vocab {head.vocab_size})"
    )
    started = time.perf_counter()
    ids = generate_greedy(head, pipe, prompt, args.generate)
    elapsed = time.perf_counter() - started
    logger.info(f"{args.generate / elapsed:.1f} tok/s")
    print(" ".join(str(t) for t in ids[0].tolist()))
    dht.shutdown()


if __name__ == "__main__":
    main()
