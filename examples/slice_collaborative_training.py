"""FULL collaborative pretraining on a multi-host slice: the whole mesh is ONE
swarm peer running the complete `Optimizer` semantics — target_batch_size epochs,
swarm GRADIENT averaging (large-batch equivalence), progress tracker, periodic
state averaging, and collective state download for late joiners.

This is the v4-32 story (VERDICT r3 next-round #1): where
``examples/slice_training.py`` runs the local-SGD family (local steps +
parameter averaging through ``SliceAverager``), this example accumulates
gradients ON DEVICE toward the swarm's virtual batch and steps optax only at
epoch boundaries, in lockstep with every other peer of the run — host peers,
GPU boxes, and other slices all matchmake in the same swarm
(reference semantics: hivemind/optim/optimizer.py:32-790).

2-process CPU rehearsal of a multi-host topology:

    python examples/slice_collaborative_training.py --platform cpu \
        --devices_per_proc 4 --num_processes 2 --process_id 0 \
        --coordinator 127.0.0.1:9912 &
    python examples/slice_collaborative_training.py --platform cpu \
        --devices_per_proc 4 --num_processes 2 --process_id 1 \
        --coordinator 127.0.0.1:9912

Process 0 prints its DHT address; plain host peers join the same ``--run_id``
with ``hivemind_tpu.optim.Optimizer`` and the slice averages gradients with them.
On a real slice drop ``--devices_per_proc`` and run one process per host.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--run_id", default="slice_collab")
    parser.add_argument("--coordinator", default=None)
    parser.add_argument("--num_processes", type=int, default=1)
    parser.add_argument("--process_id", type=int, default=0)
    parser.add_argument("--devices_per_proc", type=int, default=0)
    parser.add_argument("--initial_peers", nargs="*", default=[],
                        help="swarm bootstrap (used by process 0 only)")
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--target_batch_size", type=int, default=256,
                        help="GLOBAL samples per virtual epoch, swarm-wide")
    parser.add_argument("--batch_size", type=int, default=32,
                        help="global samples per step contributed by this slice")
    parser.add_argument("--dim", type=int, default=64)
    parser.add_argument("--learning_rate", type=float, default=0.05)
    parser.add_argument("--target_group_size", type=int, default=2)
    parser.add_argument("--delay_grad_averaging", action="store_true",
                        help="overlap swarm rounds with training (the DPU mode: "
                             "the round runs in the background and its update "
                             "lands one epoch stale — the mesh never stalls)")
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()
    if args.devices_per_proc > 0:
        kept = [
            flag for flag in os.environ.get("XLA_FLAGS", "").split()
            if not flag.startswith("--xla_force_host_platform_device_count")
        ]
        os.environ["XLA_FLAGS"] = " ".join(
            kept + [f"--xla_force_host_platform_device_count={args.devices_per_proc}"]
        )
    apply_platform(args)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    if args.coordinator:
        jax.distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.optim import SliceOptimizer
    from hivemind_tpu.utils.logging import get_logger

    logger = get_logger(f"slice_collab.p{jax.process_index()}")
    devices = np.array(jax.devices())
    mesh = Mesh(devices.reshape(-1), ("dp",))
    logger.info(f"mesh: {devices.size} devices across {jax.process_count()} processes")

    rng = np.random.RandomState(0)  # same init everywhere (replicated params)
    params = {
        "w": jax.device_put(
            rng.randn(args.dim, args.dim).astype(np.float32) * 0.1,
            NamedSharding(mesh, P()),
        ),
        "b": jax.device_put(np.zeros(args.dim, np.float32), NamedSharding(mesh, P())),
    }
    target_w = np.eye(args.dim, dtype=np.float32)
    optimizer = optax.sgd(args.learning_rate)

    @jax.jit
    def loss_and_grads(params, x, y):
        def loss_fn(p):
            pred = x @ p["w"] + p["b"]
            return jnp.mean((pred - y) ** 2)

        return jax.value_and_grad(loss_fn)(params)

    def dht_factory():
        dht = DHT(initial_peers=args.initial_peers, start=True)
        for maddr in dht.get_visible_maddrs():
            logger.info(f"swarm members can join via: --initial_peers {maddr}")
        return dht

    opt = SliceOptimizer(
        mesh=mesh, params=params, optimizer=optimizer, dht_factory=dht_factory,
        run_id=args.run_id, target_batch_size=args.target_batch_size,
        batch_size_per_step=args.batch_size,
        target_group_size=args.target_group_size, matchmaking_time=1.5,
        delay_grad_averaging=args.delay_grad_averaging,
        verbose=True,
    )

    batch_sharding = NamedSharding(mesh, P("dp"))
    data_rng = np.random.RandomState(100 + jax.process_index())
    try:
        for step in range(1, args.steps + 1):
            x_host = data_rng.randn(args.batch_size, args.dim).astype(np.float32)
            y_host = x_host @ target_w
            # each process feeds ITS OWN rows of the global batch (per-process data
            # seed): device_put with a dp sharding uploads only the rows this
            # process's devices own — real data parallelism inside the one peer
            x = jax.device_put(x_host, batch_sharding)
            y = jax.device_put(y_host, batch_sharding)
            loss, grads = loss_and_grads(opt.params, x, y)
            opt.step(grads, batch_size=args.batch_size)
            if step % 10 == 0:
                logger.info(
                    f"step {step}: loss {float(loss):.5f}, epoch {opt.local_epoch}"
                )
    finally:
        opt.shutdown()
    logger.info(f"done: epoch {opt.local_epoch}")


if __name__ == "__main__":
    main()
