"""Training monitor: join the swarm as a non-training observer and report global
progress (capability parity: reference examples/albert/run_training_monitor.py —
aggregates per-peer metrics from the DHT; wandb hookup optional)."""

from __future__ import annotations

import argparse
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--run_id", default="albert_demo")
    parser.add_argument("--initial_peers", nargs="*", required=True)
    parser.add_argument("--refresh_period", type=float, default=5.0)
    parser.add_argument("--max_reports", type=int, default=0,
                        help="exit after this many progress reports (0 = run forever)")
    parser.add_argument("--wandb_project", default=None,
                        help="log swarm metrics to Weights & Biases (needs the "
                             "wandb package; reference monitor parity)")
    parser.add_argument("--metrics_jsonl", default=None,
                        help="append each report as a JSON line (offline "
                             "wandb-style sink; survives without any service)")
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.devices()

    from hivemind_tpu.dht import DHT, Ed25519SignatureValidator
    from hivemind_tpu.optim.progress_tracker import LocalTrainingProgress
    from hivemind_tpu.utils.logging import get_logger
    from hivemind_tpu.utils.timed_storage import get_dht_time

    logger = get_logger("monitor")
    wandb_run = None
    if args.wandb_project:
        try:
            import wandb

            wandb_run = wandb.init(project=args.wandb_project, job_type="monitor")
        except ImportError:
            logger.warning("wandb is not installed; falling back to --metrics_jsonl/logs")
    from hivemind_tpu.utils.profiling import JsonlMetricsSink

    metrics_sink = JsonlMetricsSink(args.metrics_jsonl)
    # progress records are signature-protected: without this validator their
    # signatures are never stripped and the records fail to deserialize
    dht = DHT(
        initial_peers=args.initial_peers,
        start=True,
        record_validators=[Ed25519SignatureValidator()],
    )
    progress_key = f"{args.run_id}_progress"

    reports = 0
    while True:
        time.sleep(args.refresh_period)
        result = dht.get(progress_key, latest=True)
        if result is None or not isinstance(result.value, dict):
            logger.info("no training peers visible yet")
            continue
        records = []
        for entry in result.value.values():
            try:
                records.append(LocalTrainingProgress.model_validate(entry.value))
            except Exception:
                continue
        if not records:
            continue
        epoch = max(r.epoch for r in records)
        samples = sum(r.samples_accumulated for r in records if r.epoch == epoch)
        sps = sum(r.samples_per_second for r in records if r.epoch == epoch)
        logger.info(
            f"epoch {epoch}: {len(records)} peers, {samples} samples accumulated, "
            f"{sps:.0f} samples/s aggregate"
        )
        metrics = {
            "epoch": epoch, "num_peers": len(records),
            "samples_accumulated": samples, "samples_per_second": sps,
            "time": get_dht_time(),
        }
        if wandb_run is not None:
            wandb_run.log(metrics)
        metrics_sink.log(metrics)
        reports += 1
        if args.max_reports and reports >= args.max_reports:
            break

    if wandb_run is not None:
        wandb_run.finish()
    metrics_sink.close()
    dht.shutdown()


if __name__ == "__main__":
    main()
