"""Collaborative ALBERT pretraining peer (capability parity: reference
examples/albert/run_trainer.py — the flagship recipe: every peer runs this script,
joins the swarm via the DHT, and trains one shared ALBERT with the collaborative
Optimizer; peers may come and go at any time).

Trains on synthetic MLM data so the recipe runs anywhere (real-data wiring via
HuggingFace datasets is a round-2 item, see docs/design_notes.md)."""

from __future__ import annotations

import argparse
import time

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--run_id", default="albert_demo")
    parser.add_argument("--initial_peers", nargs="*", default=[])
    parser.add_argument("--target_batch_size", type=int, default=4096)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--seq_len", type=int, default=128)
    parser.add_argument("--learning_rate", type=float, default=1e-3)
    parser.add_argument("--warmup_epochs", type=int, default=100)
    parser.add_argument("--total_epochs", type=int, default=10_000)
    parser.add_argument("--matchmaking_time", type=float, default=3.0)
    parser.add_argument("--max_steps", type=int, default=10**9)
    parser.add_argument("--client_mode", action="store_true")
    parser.add_argument("--tiny", action="store_true", help="albert-tiny config (CPU-friendly)")
    parser.add_argument("--powersgd_rank", type=int, default=0, help=">0: PowerSGD gradient compression")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.models import AlbertConfig, AlbertForMaskedLM, make_synthetic_mlm_batch, mlm_loss
    from hivemind_tpu.optim import Optimizer
    from hivemind_tpu.utils.logging import get_logger

    logger = get_logger("albert_trainer")

    dht = DHT(initial_peers=args.initial_peers, start=True)
    for maddr in dht.get_visible_maddrs():
        logger.info(f"to join this training run: --initial_peers {maddr}")

    config = AlbertConfig.tiny(max_position=args.seq_len) if args.tiny else AlbertConfig.base(max_position=args.seq_len)
    model = AlbertForMaskedLM(config)
    sample = make_synthetic_mlm_batch(jax.random.PRNGKey(0), config, args.batch_size, args.seq_len)
    params = model.init(jax.random.PRNGKey(0), sample["input_ids"][:1, :8])["params"]

    @jax.jit
    def loss_and_grad(params, batch):
        def fn(p):
            logits = model.apply({"params": p}, batch["input_ids"])
            return mlm_loss(logits, batch["labels"], batch["mlm_mask"])

        return jax.value_and_grad(fn)(params)

    grad_averager_factory = None
    grad_averager_opts = {}
    if args.powersgd_rank > 0:
        from hivemind_tpu.optim import PowerSGDGradientAverager

        logger.info(f"using PowerSGD rank {args.powersgd_rank} gradient compression")
        grad_averager_factory = PowerSGDGradientAverager
        grad_averager_opts = {"averager_rank": args.powersgd_rank}
    # the reference ALBERT recipe trains with LAMB + linear warmup + clipping;
    # schedules are epoch-keyed (one optax update per virtual epoch)
    from hivemind_tpu.moe.server.layers import lamb_with_warmup

    opt = Optimizer(
        dht=dht,
        run_id=args.run_id,
        target_batch_size=args.target_batch_size,
        params=params,
        optimizer=lamb_with_warmup(args.learning_rate, args.warmup_epochs, args.total_epochs),
        batch_size_per_step=args.batch_size,
        matchmaking_time=args.matchmaking_time,
        client_mode=args.client_mode,
        grad_averager_factory=grad_averager_factory,
        grad_averager_opts=grad_averager_opts,
        verbose=True,
    )

    rng = jax.random.PRNGKey(int(time.time() * 1000) % 2**31)
    step = 0
    loss_ema = None
    while step < args.max_steps:
        rng, batch_rng = jax.random.split(rng)
        batch = make_synthetic_mlm_batch(batch_rng, config, args.batch_size, args.seq_len)
        loss, grads = loss_and_grad(opt.params, batch)
        opt.step(grads)
        loss_value = float(loss)
        loss_ema = loss_value if loss_ema is None else 0.95 * loss_ema + 0.05 * loss_value
        step += 1
        if step % 10 == 0:
            progress = opt.tracker.global_progress
            logger.info(
                f"step {step} epoch {opt.local_epoch} loss {loss_ema:.4f} "
                f"(swarm: {progress.num_peers} peers, {progress.samples_accumulated}/"
                f"{args.target_batch_size} samples)"
            )


if __name__ == "__main__":
    main()
