"""Collaborative ALBERT pretraining peer (capability parity: reference
examples/albert/run_trainer.py — the flagship recipe: every peer runs this script,
joins the swarm via the DHT, and trains one shared ALBERT with the collaborative
Optimizer; peers may come and go at any time).

Data: pass ``--dataset_path corpus.txt`` to train on a real local corpus (see
examples/albert/data.py — self-contained tokenizer, BERT-style 80/10/10 masking;
add ``--hf_tokenizer <name>`` to use an on-disk HuggingFace dataset + cached
tokenizer instead). Without it, synthetic MLM data keeps the recipe runnable
anywhere."""

from __future__ import annotations

import argparse
import time

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--run_id", default="albert_demo")
    parser.add_argument("--model", choices=("albert", "causal"), default="albert",
                        help="albert: masked-LM flagship; causal: decoder-only "
                             "next-token pretraining (models/causal_lm.py)")
    parser.add_argument("--initial_peers", nargs="*", default=[])
    parser.add_argument("--target_batch_size", type=int, default=4096)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--seq_len", type=int, default=128)
    parser.add_argument("--learning_rate", type=float, default=1e-3)
    parser.add_argument("--warmup_epochs", type=int, default=100)
    parser.add_argument("--total_epochs", type=int, default=10_000)
    parser.add_argument("--matchmaking_time", type=float, default=3.0)
    parser.add_argument("--max_steps", type=int, default=10**9)
    parser.add_argument("--client_mode", action="store_true")
    parser.add_argument("--tiny", action="store_true", help="albert-tiny config (CPU-friendly)")
    parser.add_argument("--powersgd_rank", type=int, default=0, help=">0: PowerSGD gradient compression")
    parser.add_argument("--dataset_path", default=None, help="local text corpus (or HF dataset dir with --hf_tokenizer)")
    parser.add_argument("--hf_tokenizer", default=None, help="cached HuggingFace tokenizer name for --dataset_path")
    parser.add_argument("--vocab_path", default=None,
                        help="shared vocab file for text corpora: ALL peers must use the same token "
                             "mapping (first peer writes it, the rest load it)")
    parser.add_argument("--seed", type=int, default=None, help="data sampling seed (default: random per peer)")
    parser.add_argument("--backup_every", type=int, default=30,
                        help="healthy steps between in-memory state backups for "
                             "NaN-restore (0 disables the guard; reference "
                             "run_trainer.py:62-130)")
    parser.add_argument("--metrics_jsonl", default=None,
                        help="append per-report metrics as JSON lines (wandb-style "
                             "key/value records, offline-friendly)")
    from hivemind_tpu.utils.platform import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()
    apply_platform(args)

    import jax
    import jax.numpy as jnp
    import optax

    from hivemind_tpu.dht import DHT
    from hivemind_tpu.models import AlbertConfig, AlbertForMaskedLM, make_mlm_loss_fn, make_synthetic_mlm_batch
    from hivemind_tpu.optim import Optimizer
    from hivemind_tpu.utils.logging import get_logger

    logger = get_logger("albert_trainer")

    dht = DHT(initial_peers=args.initial_peers, start=True)
    for maddr in dht.get_visible_maddrs():
        logger.info(f"to join this training run: --initial_peers {maddr}")

    if args.model == "causal":
        from hivemind_tpu.models import CausalLM, CausalLMConfig, causal_lm_loss

        config = (
            CausalLMConfig.tiny(max_position=args.seq_len) if args.tiny
            else CausalLMConfig.base(max_position=args.seq_len)
        )
        model = CausalLM(config)
        sample = make_synthetic_mlm_batch(jax.random.PRNGKey(0), config, args.batch_size, args.seq_len)
        params = model.init(jax.random.PRNGKey(0), sample["input_ids"][:1, :8])["params"]

        def loss_fn(params, batch):
            # the sampler's "labels" field is the UNMASKED token stream — exactly
            # what next-token prediction trains on
            tokens = batch["labels"]
            return causal_lm_loss(model.apply({"params": params}, tokens), tokens)
    else:
        config = AlbertConfig.tiny(max_position=args.seq_len) if args.tiny else AlbertConfig.base(max_position=args.seq_len)
        model = AlbertForMaskedLM(config)
        sample = make_synthetic_mlm_batch(jax.random.PRNGKey(0), config, args.batch_size, args.seq_len)
        params = model.init(jax.random.PRNGKey(0), sample["input_ids"][:1, :8])["params"]

        # masked-only loss: ~4x cheaper MLM head (same objective at 15% masking)
        loss_fn = make_mlm_loss_fn(model, masked_loss_fraction=0.25)

    @jax.jit
    def loss_and_grad(params, batch):
        return jax.value_and_grad(loss_fn)(params, batch)

    grad_averager_factory = None
    grad_averager_opts = {}
    if args.powersgd_rank > 0:
        from hivemind_tpu.optim import PowerSGDGradientAverager

        logger.info(f"using PowerSGD rank {args.powersgd_rank} gradient compression")
        grad_averager_factory = PowerSGDGradientAverager
        grad_averager_opts = {"averager_rank": args.powersgd_rank}
    # the reference ALBERT recipe trains with LAMB + linear warmup + clipping;
    # schedules are epoch-keyed (one optax update per virtual epoch)
    from hivemind_tpu.moe.server.layers import lamb_with_warmup

    opt = Optimizer(
        dht=dht,
        run_id=args.run_id,
        target_batch_size=args.target_batch_size,
        params=params,
        optimizer=lamb_with_warmup(args.learning_rate, args.warmup_epochs, args.total_epochs),
        batch_size_per_step=args.batch_size,
        matchmaking_time=args.matchmaking_time,
        client_mode=args.client_mode,
        grad_averager_factory=grad_averager_factory,
        grad_averager_opts=grad_averager_opts,
        verbose=True,
    )

    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from data import make_batch_sampler

    sample_batch = make_batch_sampler(
        config, args.seq_len, dataset_path=args.dataset_path,
        hf_tokenizer=args.hf_tokenizer, vocab_path=args.vocab_path,
        seed=args.seed if args.seed is not None else int(time.time() * 1000) % 2**31,
    )
    from hivemind_tpu.optim import NaNGuard
    from hivemind_tpu.utils.profiling import JsonlMetricsSink

    guard = NaNGuard(opt, backup_every=args.backup_every) if args.backup_every > 0 else None
    metrics_sink = JsonlMetricsSink(args.metrics_jsonl)

    step = 0
    loss_ema = None
    while step < args.max_steps:
        batch = {k: jnp.asarray(v) for k, v in sample_batch(args.batch_size).items()}
        loss, grads = loss_and_grad(opt.params, batch)
        loss_value = float(loss)
        if guard is not None:
            guard.step(loss_value, grads)  # restores the backup on NaN/Inf
        else:
            opt.step(grads)
        if np.isfinite(loss_value):
            loss_ema = loss_value if loss_ema is None else 0.95 * loss_ema + 0.05 * loss_value
        step += 1
        if step % 10 == 0:
            progress = opt.tracker.global_progress
            ema_text = f"{loss_ema:.4f}" if loss_ema is not None else "n/a"
            logger.info(
                f"step {step} epoch {opt.local_epoch} loss {ema_text} "
                f"(swarm: {progress.num_peers} peers, {progress.samples_accumulated}/"
                f"{args.target_batch_size} samples)"
                + (f" [{guard.restores} NaN restores]" if guard is not None and guard.restores else "")
            )
            metrics_sink.log({
                "step": step, "epoch": opt.local_epoch, "loss": loss_value,
                "loss_ema": loss_ema, "num_peers": progress.num_peers,
                "samples_accumulated": progress.samples_accumulated,
                "time": time.time(),
            })

    # reached max_steps (benchmarks/smoke runs): leave the swarm cleanly so the
    # process actually exits instead of hanging on background threads
    final_text = f"{loss_ema:.4f}" if loss_ema is not None else "n/a"
    logger.info(f"training finished after {step} steps at epoch {opt.local_epoch}, final loss {final_text}")
    metrics_sink.close()
    opt.shutdown()
    dht.shutdown()


if __name__ == "__main__":
    main()
