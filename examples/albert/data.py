"""Real-data MLM batches for the collaborative ALBERT recipe (fills the reference's
examples/albert data pipeline role, run_trainer.py + HF datasets/tokenizers).

Two tiers, so the recipe works on air-gapped machines and scales up when the HF
stack has local assets:

1. :class:`TextMLMDataset` — self-contained: builds a frequency vocabulary from a
   local text corpus, encodes it into one token stream, and samples BERT-style
   masked-LM batches (15% selection, 80/10/10 mask/random/keep). Zero downloads.
   COLLABORATIVE CAVEAT: every peer gradient-averages ONE shared model, so all
   peers must share one token mapping — either train from the same corpus file or
   pass ``vocab_path`` pointing at a shared vocab file (written by the first peer,
   loaded by the rest).
2. :func:`load_hf_mlm_dataset` — when a HuggingFace tokenizer + dataset are
   available ON DISK (``datasets.load_from_disk`` / cached tokenizer), use them
   instead; the tokenizer itself is the shared vocabulary.

Batch schema matches ``hivemind_tpu.models.make_synthetic_mlm_batch``:
``{"input_ids", "labels", "mlm_mask"}`` with shapes [batch, seq_len]."""

from __future__ import annotations

import collections
import os
import re
from typing import Callable, Dict, List, Optional

import numpy as np

PAD, CLS, SEP, MASK, UNK = 0, 1, 2, 3, 4
NUM_SPECIAL = 5
_TOKEN_RE = re.compile(r"[\w']+|[^\w\s]")


def _apply_mlm_mask(
    labels: np.ndarray,
    selected: np.ndarray,
    rng: np.random.RandomState,
    mask_id: int,
    vocab_size: int,
) -> np.ndarray:
    """BERT 80/10/10: of the selected positions, 80% -> [MASK], 10% -> random token,
    10% -> unchanged; the loss is taken on ALL selected positions."""
    roll = rng.rand(*labels.shape)
    input_ids = labels.copy()
    input_ids[selected & (roll < 0.8)] = mask_id
    random_positions = selected & (roll >= 0.8) & (roll < 0.9)
    input_ids[random_positions] = rng.randint(
        NUM_SPECIAL, vocab_size, size=int(random_positions.sum())
    )
    return input_ids


class TextMLMDataset:
    """Masked-LM batches from a local text file. See module docstring."""

    def __init__(
        self,
        path: str,
        vocab_size: int,
        seq_len: int,
        mask_prob: float = 0.15,
        vocab_path: Optional[str] = None,
    ):
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            text = f.read()
        words = _TOKEN_RE.findall(text.lower())
        if not words:
            raise ValueError(f"corpus {path!r} contains no tokens")
        if vocab_path is not None and os.path.exists(vocab_path):
            with open(vocab_path, "r", encoding="utf-8") as f:
                word_list = [line.rstrip("\n") for line in f if line.rstrip("\n")]
        else:
            counts = collections.Counter(words)
            word_list = [w for w, _ in counts.most_common(vocab_size - NUM_SPECIAL)]
            if vocab_path is not None:
                with open(vocab_path, "w", encoding="utf-8") as f:
                    f.write("\n".join(word_list) + "\n")
        self.vocab = {w: i + NUM_SPECIAL for i, w in enumerate(word_list[: vocab_size - NUM_SPECIAL])}
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self.mask_prob = mask_prob
        self.stream = np.array([self.vocab.get(w, UNK) for w in words], dtype=np.int32)
        if len(self.stream) < seq_len:
            self.stream = np.tile(self.stream, seq_len // len(self.stream) + 1)

    def sample_batch(self, rng: np.random.RandomState, batch_size: int) -> Dict[str, np.ndarray]:
        starts = rng.randint(0, len(self.stream) - self.seq_len + 1, size=batch_size)
        labels = np.stack([self.stream[s : s + self.seq_len] for s in starts])
        selected = rng.rand(batch_size, self.seq_len) < self.mask_prob
        input_ids = _apply_mlm_mask(labels, selected, rng, MASK, self.vocab_size)
        return {"input_ids": input_ids, "labels": labels, "mlm_mask": selected}


def load_hf_mlm_dataset(
    dataset_path: str, tokenizer_name: str, vocab_size: int, seq_len: int
) -> "HFMLMDataset":
    """Local-disk HuggingFace pipeline (no downloads: load_from_disk + cached
    tokenizer). Raises ImportError/OSError when the assets are not available."""
    from datasets import load_from_disk
    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(tokenizer_name, local_files_only=True)
    dataset = load_from_disk(dataset_path)
    return HFMLMDataset(dataset, tokenizer, vocab_size, seq_len)


class HFMLMDataset:
    def __init__(self, dataset, tokenizer, vocab_size: int, seq_len: int, mask_prob: float = 0.15):
        if hasattr(dataset, "keys") and not hasattr(dataset, "features"):
            # a DatasetDict of splits: train on its training split
            split = "train" if "train" in dataset else next(iter(dataset))
            dataset = dataset[split]
        if len(tokenizer) > vocab_size:
            raise ValueError(
                f"tokenizer {tokenizer.name_or_path!r} has {len(tokenizer)} tokens but the "
                f"model vocab_size is {vocab_size}; configure the model with "
                f"vocab_size >= {len(tokenizer)} (silently clamping ids would corrupt labels)"
            )
        self.dataset, self.tokenizer = dataset, tokenizer
        self.vocab_size, self.seq_len, self.mask_prob = vocab_size, seq_len, mask_prob
        self.mask_id = tokenizer.mask_token_id if tokenizer.mask_token_id is not None else MASK
        self.text_column = "text" if "text" in dataset.column_names else dataset.column_names[0]

    def sample_batch(self, rng: np.random.RandomState, batch_size: int) -> Dict[str, np.ndarray]:
        rows = rng.randint(0, len(self.dataset), size=batch_size)
        texts: List[str] = [self.dataset[int(r)][self.text_column] or " " for r in rows]
        encoded = self.tokenizer(
            texts, max_length=self.seq_len, truncation=True, padding="max_length",
            return_tensors="np",
        )
        labels = encoded["input_ids"].astype(np.int32)
        attention = encoded["attention_mask"].astype(bool)
        selected = (rng.rand(*labels.shape) < self.mask_prob) & attention
        input_ids = _apply_mlm_mask(labels, selected, rng, self.mask_id, self.vocab_size)
        return {"input_ids": input_ids, "labels": labels, "mlm_mask": selected}


def make_batch_sampler(
    config,
    seq_len: int,
    dataset_path: Optional[str] = None,
    hf_tokenizer: Optional[str] = None,
    vocab_path: Optional[str] = None,
    seed: int = 0,
) -> Callable[[int], Dict]:
    """The trainer's data entrypoint: real corpus when given, synthetic otherwise.
    The synthetic sampler returns device (jnp) arrays — no host round trip."""
    if hf_tokenizer is not None and dataset_path is None:
        raise ValueError("--hf_tokenizer requires --dataset_path (an on-disk HF dataset dir)")
    rng = np.random.RandomState(seed)
    if dataset_path is not None and hf_tokenizer is not None:
        dataset = load_hf_mlm_dataset(dataset_path, hf_tokenizer, config.vocab_size, seq_len)
        return lambda batch_size: dataset.sample_batch(rng, batch_size)
    if dataset_path is not None:
        dataset = TextMLMDataset(dataset_path, config.vocab_size, seq_len, vocab_path=vocab_path)
        return lambda batch_size: dataset.sample_batch(rng, batch_size)

    import jax

    from hivemind_tpu.models import make_synthetic_mlm_batch

    def synthetic(batch_size: int):
        key = jax.random.PRNGKey(rng.randint(0, 2**31 - 1))
        return make_synthetic_mlm_batch(key, config, batch_size, seq_len)

    return synthetic
